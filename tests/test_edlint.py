"""Tier-1 wiring + self-tests for the edlint analyzer
(elasticdl_tpu/tools/edlint, docs/static_analysis.md).

Three layers:

- the tree gate: ``python -m elasticdl_tpu.tools.edlint`` must exit 0
  over this repo with ALL ELEVEN rules active (the whole-program pass —
  cross-file call graph, thread roots, R8 lockset race detection, R9
  RPC retry-safety, R10 copy-on-wire, R11 lock-order deadlock
  detection — included), and every allowlist
  ratchet entry must carry a reason (the acceptance bar);
- known-bad fixtures per rule R1–R11, each paired with the safe idiom
  the rule must NOT flag — the R4/R5/R6 bad fixtures are the REAL
  pre-fix violations PR 4 fixed; the cross-file R5 fixture re-splits
  the PR-4 ledger-lock chain across a module boundary (the shape only
  the whole-program lift can see); the R8 race fixture is additionally
  executed under the runtime lock-order sanitizer to pin that the
  static rule catches what the sanitizer structurally cannot;
- engine mechanics: the ratchet counts per (rule, file), the
  ``--stale`` only-shrinks check, the mtime-keyed AST cache, and the
  ``--json`` machine output check.sh consumes.
"""

import json
import os
import subprocess
import sys

import pytest

from elasticdl_tpu.tools.edlint.core import (
    apply_ratchet,
    run,
    scan,
    stale_entries,
)
from elasticdl_tpu.tools.edlint.ratchet import ALLOW

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _isolated_edlint_cache(tmp_path_factory, monkeypatch):
    # every fixture-tree scan (in-process or subprocess — the env is
    # inherited) writes its AST cache under a throwaway dir instead of
    # accumulating per-tmp-root pickles in the user's real ~/.cache
    monkeypatch.setenv(
        "XDG_CACHE_HOME", str(tmp_path_factory.mktemp("edlint-xdg"))
    )


_case = [0]


def _plant(tmp_path, source, relpath, extra=None):
    """A FRESH scratch tree holding ``source`` at ``relpath`` (+ any
    ``extra`` {relpath: source} modules for cross-file fixtures)."""
    _case[0] += 1
    root = tmp_path / ("case%d" % _case[0])
    for rel, src in dict(extra or {}, **{relpath: source}).items():
        target = root / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(src)
    return root


def _lint(tmp_path, source, relpath="elasticdl_tpu/fixture.py", extra=None):
    """Rule ids found in ``source`` planted at ``relpath`` of a FRESH
    scratch tree (one per call, so fixtures never see each other; the
    ratchet keys on repo paths, so scratch files never hit allowlist
    budgets)."""
    root = _plant(tmp_path, source, relpath, extra)
    findings, broken = scan(str(root))
    assert not broken, broken
    violations, _, _ = apply_ratchet(findings)
    return violations


def _rules_of(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# the tree gate
# ---------------------------------------------------------------------------


def test_tree_is_clean_under_all_eleven_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "elasticdl_tpu.tools.edlint", "--stale"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=ROOT,
    )
    assert proc.returncode == 0, (
        "edlint tripped on the tree:\n" + proc.stdout + proc.stderr
    )


def test_every_ratchet_entry_carries_a_reason():
    assert ALLOW, "ratchet exists"
    for rule_id, files in ALLOW.items():
        for path, entry in files.items():
            assert entry.get("max", 0) > 0, (rule_id, path)
            reason = entry.get("reason", "")
            assert isinstance(reason, str) and len(reason) > 20, (
                "allowlist entry without a substantive reason: "
                "%s %s" % (rule_id, path)
            )


def test_greps_guard_message_compat(tmp_path):
    """The retired regex guard's report vocabulary survives in R1/R2
    (tests/test_greps_guard.py pins the subprocess contract against
    edlint directly now that the shim is deleted)."""
    violations = _lint(
        tmp_path,
        "import jax\nimport queue\n"
        "def probe():\n"
        "    return jax.devices()\n"
        "def feed(q, item):\n"
        "    q.put(item)\n",
    )
    messages = "\n".join(v.message for v in violations)
    assert "jax.devices() outside escapable_call" in messages
    assert "queue put without timeout+cancel" in messages


# ---------------------------------------------------------------------------
# R1 — device probe
# ---------------------------------------------------------------------------


def test_r1_flags_calls_but_not_the_escapable_passthrough(tmp_path):
    bad = _lint(
        tmp_path,
        "import jax\n"
        "def probe():\n"
        "    return len(jax.devices())\n",
    )
    assert _rules_of(bad) == ["R1"]
    good = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.common.escapable import escapable_call\n"
        "def probe():\n"
        "    # jax.devices passes UNCALLED: the safe idiom the old\n"
        "    # regex needed a backtick heuristic to avoid flagging\n"
        "    return escapable_call(jax.devices, timeout=30)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R2 — queue put discipline
# ---------------------------------------------------------------------------


def test_r2_receiver_typing_and_boundedness(tmp_path):
    bad = _lint(
        tmp_path,
        "import queue\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._jobs = queue.Queue(maxsize=4)\n"
        "    def feed(self, item):\n"
        "        self._jobs.put(item)\n",
    )
    assert _rules_of(bad) == ["R2"], bad
    good = _lint(
        tmp_path,
        "import queue\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        # unbounded: put never blocks — safe BY CONSTRUCTION,\n"
        "        # no allowlist entry needed (the regex guard had to\n"
        "        # ratchet exactly this shape by hand)\n"
        "        self._jobs = queue.Queue()\n"
        "    def feed(self, item, cancel, q):\n"
        "        self._jobs.put(item)\n"
        "        while not cancel.is_set():\n"
        "            try:\n"
        "                q.put(item, timeout=0.5)\n"
        "                return True\n"
        "            except queue.Full:\n"
        "                continue\n"
        "        return False\n"
        "    def cache_fill(self, cache, k, v):\n"
        "        cache.put(k, v)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R3 — data-plane queue get discipline
# ---------------------------------------------------------------------------


def test_r3_scoped_to_data_plane_with_receiver_typing(tmp_path):
    src = (
        "import queue\n"
        "def consume(opts):\n"
        "    q = queue.Queue(maxsize=1)\n"
        "    item = q.get()\n"
        "    mode = opts.get('mode')\n"  # dict .get: not a queue
        "    return item, mode\n"
    )
    bad = _lint(tmp_path, src, relpath="elasticdl_tpu/data/fixture.py")
    assert _rules_of(bad) == ["R3"], bad
    assert len(bad) == 1  # the dict .get did not count
    # identical code OUTSIDE the data plane is out of R3's scope
    assert not _lint(
        tmp_path, src, relpath="elasticdl_tpu/master/fixture.py"
    )
    good = _lint(
        tmp_path,
        "import queue\n"
        "def consume(cancel):\n"
        "    q = queue.Queue(maxsize=1)\n"
        "    while not cancel.is_set():\n"
        "        try:\n"
        "            return q.get(timeout=0.2)\n"
        "        except queue.Empty:\n"
        "            continue\n"
        "    return q.get_nowait()\n",
        relpath="elasticdl_tpu/data/fixture.py",
    )
    assert not good


# ---------------------------------------------------------------------------
# R4 — thread lifecycle (real pre-fix violation: k8s_client's watcher)
# ---------------------------------------------------------------------------

R4_PREFIX_VIOLATION = """
import threading

class Client:
    # pre-fix common/k8s_client.py: fire-and-forget daemon watcher,
    # no stop/close path anywhere on the owning class — the stream
    # thread could only be abandoned, never collected
    def __init__(self, event_cb):
        self._event_cb = event_cb
        threading.Thread(
            target=self._watch, name="event_watcher", daemon=True
        ).start()

    def _watch(self):
        while True:
            self._event_cb()
"""

R4_FIXED = """
import threading

class Client:
    # the fix that shipped: the thread is held, and close() gives the
    # class a shutdown path
    def __init__(self, event_cb):
        self._event_cb = event_cb
        self._watch_thread = threading.Thread(
            target=self._watch, name="event_watcher", daemon=True
        )
        self._watch_thread.start()

    def _watch(self):
        while True:
            self._event_cb()

    def close(self):
        self._watch_thread.join(timeout=5.0)
"""


def test_r4_pins_the_prefix_k8s_watcher_violation(tmp_path):
    assert _rules_of(_lint(tmp_path, R4_PREFIX_VIOLATION)) == ["R4"]
    assert not _lint(tmp_path, R4_FIXED)


def test_r4_non_daemon_thread_must_be_joined(tmp_path):
    bad = _lint(
        tmp_path,
        "import threading\n"
        "def fire(fn):\n"
        "    threading.Thread(target=fn).start()\n",
    )
    assert _rules_of(bad) == ["R4"]
    good = _lint(
        tmp_path,
        "import threading\n"
        "def run(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
        "    t.join()\n",
    )
    assert not good


def test_r4_cancel_event_counts_as_shutdown_path(tmp_path):
    # the Dataset.prefetch idiom: generator finally sets the
    # producer's cancel event — a cancel path without a method name
    good = _lint(
        tmp_path,
        "import threading\n"
        "class D:\n"
        "    def stream(self):\n"
        "        cancel = threading.Event()\n"
        "        def produce():\n"
        "            while not cancel.is_set():\n"
        "                pass\n"
        "        t = threading.Thread(target=produce, daemon=True)\n"
        "        t.start()\n"
        "        try:\n"
        "            yield 1\n"
        "        finally:\n"
        "            cancel.set()\n",
    )
    assert not good


def test_r4_executor_must_be_shut_down(tmp_path):
    bad = _lint(
        tmp_path,
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=2)\n",
    )
    assert _rules_of(bad) == ["R4"]
    good = _lint(
        tmp_path,
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=2)\n"
        "    def close(self):\n"
        "        self._pool.shutdown(wait=True)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R5 — blocking under lock (real pre-fix violation: the ack RPC chain)
# ---------------------------------------------------------------------------

R5_PREFIX_VIOLATION = """
import threading

class TaskDataService:
    # pre-fix worker/task_data_service.py: report_record_done held the
    # ledger lock across _drain_acknowledged -> _acknowledge -> the
    # report_task_result MASTER RPC — a full round trip serializing
    # the fetcher's round checks and any concurrent spare-park requeue.
    # Lexically the RPC is two calls deep: only the transitive pass
    # sees it.
    def __init__(self, worker):
        self._worker = worker
        self._ledger_lock = threading.Lock()
        self._inflight = []

    def report_record_done(self, count):
        with self._ledger_lock:
            self._drain_acknowledged()

    def _drain_acknowledged(self):
        while self._inflight:
            self._acknowledge(self._inflight.pop())

    def _acknowledge(self, task):
        self._worker.report_task_result(task, "")
"""

R5_FIXED = """
import threading

class TaskDataService:
    # the fix that shipped: snapshot under the lock, send after release
    def __init__(self, worker):
        self._worker = worker
        self._ledger_lock = threading.Lock()
        self._inflight = []

    def report_record_done(self, count):
        outbox = []
        with self._ledger_lock:
            self._drain_acknowledged(outbox)
        for task in outbox:
            self._worker.report_task_result(task, "")

    def _drain_acknowledged(self, outbox):
        while self._inflight:
            outbox.append(self._inflight.pop())
"""


def test_r5_pins_the_prefix_ack_rpc_chain(tmp_path):
    bad = _lint(tmp_path, R5_PREFIX_VIOLATION)
    assert _rules_of(bad) == ["R5"]
    assert "report_task_result" in bad[0].message  # names the sink
    assert not _lint(tmp_path, R5_FIXED)


def test_r5_direct_blocking_forms(tmp_path):
    bad = _lint(
        tmp_path,
        "import threading, time\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def step(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.5)\n",
    )
    assert _rules_of(bad) == ["R5"]


def test_r5_sees_acquire_try_finally_release_regions(tmp_path):
    bad = _lint(
        tmp_path,
        "import threading, time\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def step(self):\n"
        "        self._lock.acquire()\n"
        "        try:\n"
        "            time.sleep(0.5)\n"
        "        finally:\n"
        "            self._lock.release()\n",
    )
    assert _rules_of(bad) == ["R5"]


def test_r5_condition_wait_under_its_own_lock_is_fine(tmp_path):
    good = _lint(
        tmp_path,
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "    def step(self):\n"
        "        with self._cond:\n"
        "            self._cond.wait(timeout=1.0)\n",
    )
    assert not good


# the PR-18 micro-batcher scope: inside serving/batcher.py the queue
# lock must stay dispatch- and copy-free — a jitted forward or a
# padding concatenate under it serializes every concurrent submitter
# behind the slowest thing in the file (docs/serving.md)
BATCHER_R5_BAD = """
import threading

import numpy as np


class B:
    def __init__(self, scorer):
        self._mu = threading.Lock()
        self._scorer = scorer
        self._queue = []

    def submit(self, feats):
        with self._mu:
            self._queue.append(feats)
            batch = np.concatenate(self._queue)
            out = self._scorer.score(batch)
            self._queue = []
        return out
"""

BATCHER_R5_GOOD = """
import threading

import numpy as np


class B:
    def __init__(self, scorer):
        self._mu = threading.Lock()
        self._scorer = scorer
        self._queue = []

    def submit(self, feats):
        with self._mu:
            self._queue.append(feats)
            take, self._queue = self._queue, []
        batch = np.concatenate(take)
        return self._scorer.score(batch)
"""


def test_r5_batcher_no_dispatch_or_padding_copy_under_lock(tmp_path):
    bad = _lint(
        tmp_path,
        BATCHER_R5_BAD,
        relpath="elasticdl_tpu/serving/batcher.py",
    )
    assert _rules_of(bad) == ["R5"], bad
    kinds = " ".join(v.message for v in bad)
    assert "jit dispatch" in kinds, kinds
    assert "padding copy" in kinds, kinds
    # snapshot-under-lock, assemble-and-score after release: clean
    good = _lint(
        tmp_path,
        BATCHER_R5_GOOD,
        relpath="elasticdl_tpu/serving/batcher.py",
    )
    assert not good


def test_r5_batcher_scope_is_the_batcher_file(tmp_path):
    """score/concatenate are ordinary compute everywhere else — the
    dispatch/padding kinds only arm inside serving/batcher.py."""
    elsewhere = _lint(
        tmp_path,
        BATCHER_R5_BAD,
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert "R5" not in _rules_of(elsewhere), elsewhere


def test_r8_serving_plane_joined_the_lockset_scope(tmp_path):
    """PR-18 made the serving plane's request path multi-threaded by
    construction (submitters x dispatcher x watcher x sync), so
    serving/ files now gate under the R8 lockset-race rule."""
    bad = _lint(
        tmp_path, R8_RACE, relpath="elasticdl_tpu/serving/fixture.py"
    )
    assert _rules_of(bad) == ["R8"], bad
    good = _lint(
        tmp_path, R8_LOCKED, relpath="elasticdl_tpu/serving/fixture.py"
    )
    assert not good


# ---------------------------------------------------------------------------
# R6 — silent broad except (real pre-fix violation: worker/main's
# swallowed leave announcement)
# ---------------------------------------------------------------------------

R6_PREFIX_VIOLATION = """
def announce_leave(stub, worker_id):
    # pre-fix worker/main.py: a missed leave announcement vanished —
    # nothing in any log tied a later spurious reform to this miss
    try:
        if stub is not None:
            stub.leave_comm_world(worker_id)
    except Exception:
        pass
"""

R6_FIXED = """
import logging
logger = logging.getLogger(__name__)

def announce_leave(stub, worker_id):
    try:
        if stub is not None:
            stub.leave_comm_world(worker_id)
    except Exception:
        logger.debug("leave announcement missed", exc_info=True)
"""


def test_r6_pins_the_prefix_silent_swallow(tmp_path):
    assert _rules_of(_lint(tmp_path, R6_PREFIX_VIOLATION)) == ["R6"]
    assert not _lint(tmp_path, R6_FIXED)


def test_r6_narrowed_types_pass(tmp_path):
    good = _lint(
        tmp_path,
        "def load_native():\n"
        "    try:\n"
        "        import ctypes\n"
        "        return ctypes\n"
        "    except (ImportError, OSError):\n"
        "        pass\n"
        "    return None\n",
    )
    assert not good


def test_r6_reraise_and_real_work_pass(tmp_path):
    good = _lint(
        tmp_path,
        "def f(x):\n"
        "    try:\n"
        "        return 1 / x\n"
        "    except Exception:\n"
        "        raise RuntimeError('bad x') from None\n"
        "def g(x, fallback):\n"
        "    try:\n"
        "        return 1 / x\n"
        "    except Exception:\n"
        "        return fallback(x)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R7 — jit purity
# ---------------------------------------------------------------------------

R7_BAD = """
import jax

class Trainer:
    def make_step(self, opt):
        def step(ts, batch):
            # host side effects inside traced code: the print fires
            # once per TRACE (then silently never again), and the
            # self-mutation records only the tracer's abstract value
            print("step", ts.version)
            self.last_batch = batch
            return opt.update(ts, batch)
        return jax.jit(step, donate_argnums=(0,))
"""

R7_GOOD = """
import jax
import jax.numpy as jnp

def make_step(opt):
    def step(ts, batch):
        jax.debug.print("step {v}", v=ts.version)  # trace-aware: fine
        loss = jnp.sum(batch)
        return opt.update(ts, batch), loss
    return jax.jit(step, donate_argnums=(0,))

@jax.jit
def fwd(params, x):
    return params @ x
"""


def test_r7_flags_host_effects_in_traced_functions(tmp_path):
    bad = _lint(tmp_path, R7_BAD)
    assert _rules_of(bad) == ["R7"]
    assert not _lint(tmp_path, R7_GOOD)


def test_r7_flags_telemetry_registry_calls_in_traced_code(tmp_path):
    bad = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils import profiling\n"
        "def step(ts, batch):\n"
        "    profiling.counters.inc('step/hits')\n"
        "    return ts\n"
        "jax.jit(step)\n",
    )
    assert _rules_of(bad) == ["R7"]
    assert "records telemetry" in bad[0].message
    # the same call OUTSIDE traced code is the intended idiom
    good = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils import profiling\n"
        "def step(ts, batch):\n"
        "    return ts\n"
        "def drive(ts, batch):\n"
        "    profiling.counters.inc('step/hits')\n"
        "    profiling.events.emit('resize_begin')\n"
        "    return jax.jit(step)(ts, batch)\n",
    )
    assert not good


def test_r7_flags_span_emission_in_traced_code(tmp_path):
    """Opening a tracing span inside jit-reachable scope is a finding:
    the span would time the TRACE (once, at compile) rather than the
    step, then silently never record again (docs/observability.md)."""
    bad = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils import profiling\n"
        "def step(ts, batch):\n"
        "    with profiling.span('step/compute'):\n"
        "        return ts\n"
        "jax.jit(step)\n",
    )
    assert _rules_of(bad) == ["R7"]
    assert "records telemetry" in bad[0].message
    bad_begin = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils.profiling import spans\n"
        "def step(ts, batch):\n"
        "    spans.begin('step/compute')\n"
        "    return ts\n"
        "jax.jit(step)\n",
    )
    assert _rules_of(bad_begin) == ["R7"]
    # the intended idiom: the span wraps the DISPATCH, outside trace
    good = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils import profiling\n"
        "def step(ts, batch):\n"
        "    return ts\n"
        "def drive(ts, batch):\n"
        "    with profiling.span('step/compute'):\n"
        "        return jax.jit(step)(ts, batch)\n",
    )
    assert not good


def test_r7_sees_decorator_and_shard_map_forms(tmp_path):
    bad = _lint(
        tmp_path,
        "import jax, functools, logging\n"
        "logger = logging.getLogger(__name__)\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def step(ts, batch):\n"
        "    logger.info('stepping %s', ts)\n"
        "    return ts\n"
        "def build(mesh, shard_map):\n"
        "    def body(tree):\n"
        "        global _seen\n"
        "        _seen = tree\n"
        "        return tree\n"
        "    return jax.jit(shard_map(body, mesh=mesh))\n",
    )
    assert _rules_of(bad) == ["R7"]
    assert len(bad) == 2


# ---------------------------------------------------------------------------
# R7/R8 — the layout solver's by-construction pins (ISSUE 20): the
# solver file must hold NO jax import and NO synchronization primitive,
# because it runs on the establish path of every process and inside the
# speculative compiler's daemon thread simultaneously.
# ---------------------------------------------------------------------------

SOLVER_PATH = "elasticdl_tpu/parallel/layout_solver.py"

SOLVER_GOOD = """
import math
import os


def solve(n_devices, degrees):
    out = []
    for tp in sorted(degrees):
        if n_devices % tp == 0:
            out.append((n_devices // tp, tp))
    return out
"""

SOLVER_BAD_JIT = """
import jax


def score(layouts):
    return jax.jit(lambda xs: xs)(layouts)
"""

SOLVER_BAD_LOCK = """
import threading


class Planner:
    def __init__(self):
        self._lock = threading.Lock()

    def plan(self, n):
        with self._lock:
            return n
"""


def test_r7_pins_layout_solver_jit_free(tmp_path):
    bad = _lint(tmp_path, SOLVER_BAD_JIT, relpath=SOLVER_PATH)
    assert "R7" in _rules_of(bad)
    msgs = " | ".join(v.message for v in bad if v.rule == "R7")
    assert "jit-free by construction" in msgs
    # both the import and the jit call site are findings
    assert "importing" in msgs and "call sites" in msgs
    assert not _lint(tmp_path, SOLVER_GOOD, relpath=SOLVER_PATH)
    # the SAME source anywhere else is fine — the pin is path-scoped
    assert not _lint(
        tmp_path, SOLVER_BAD_JIT, relpath="elasticdl_tpu/fixture.py"
    )


def test_r8_pins_layout_solver_lock_free(tmp_path):
    bad = _lint(tmp_path, SOLVER_BAD_LOCK, relpath=SOLVER_PATH)
    assert "R8" in _rules_of(bad)
    msgs = " | ".join(v.message for v in bad if v.rule == "R8")
    assert "lock-free by construction" in msgs
    assert not _lint(tmp_path, SOLVER_GOOD, relpath=SOLVER_PATH)


def test_real_layout_solver_satisfies_its_own_pins():
    """The shipped solver passes the by-construction checks (no jax
    import, no lock), so the pins gate regressions, not the present."""
    with open(os.path.join(ROOT, SOLVER_PATH)) as f:
        src = f.read()
    assert "import jax" not in src
    assert "threading" not in src


# ---------------------------------------------------------------------------
# R5 cross-file: the PR-4 ledger-lock chain THROUGH A MODULE BOUNDARY
# ---------------------------------------------------------------------------

R5_XFILE_CALLER = """
import threading

from elasticdl_tpu.worker.ack_ledger import drain_acknowledged


class TaskDataService:
    # the PR-4 pre-fix ledger-lock shape with the drain helper moved to
    # its own module: lexically there is no blocking call in this file
    # at all — only the whole-program call graph can see that the
    # master RPC still runs under the ledger lock
    def __init__(self, worker):
        self._worker = worker
        self._ledger_lock = threading.Lock()
        self._inflight = []

    def report_record_done(self, count):
        with self._ledger_lock:
            drain_acknowledged(self._inflight, self._worker)
"""

R5_XFILE_CALLEE = """
def drain_acknowledged(inflight, worker):
    while inflight:
        _acknowledge(inflight.pop(), worker)


def _acknowledge(task, worker):
    worker.report_task_result(task, "")
"""

R5_XFILE_FIXED_CALLER = """
import threading

from elasticdl_tpu.worker.ack_ledger import snapshot_acknowledged


class TaskDataService:
    # the shipped fix, same module split: snapshot under the lock,
    # send after release
    def __init__(self, worker):
        self._worker = worker
        self._ledger_lock = threading.Lock()
        self._inflight = []

    def report_record_done(self, count):
        with self._ledger_lock:
            outbox = snapshot_acknowledged(self._inflight)
        for task in outbox:
            self._worker.report_task_result(task, "")
"""

R5_XFILE_FIXED_CALLEE = """
def snapshot_acknowledged(inflight):
    outbox = []
    while inflight:
        outbox.append(inflight.pop())
    return outbox
"""


def test_r5_cross_file_ledger_lock_chain(tmp_path):
    """Acceptance bar: the PR-4 ledger-lock finding reproduces from its
    pre-fix fixture with caller and blocking callee split across
    files."""
    bad = _lint(
        tmp_path,
        R5_XFILE_CALLER,
        relpath="elasticdl_tpu/worker/task_data_service.py",
        extra={"elasticdl_tpu/worker/ack_ledger.py": R5_XFILE_CALLEE},
    )
    assert _rules_of(bad) == ["R5"], bad
    # the chain names the blocking sink across both hops
    assert "drain_acknowledged" in bad[0].message
    assert "report_task_result" in bad[0].message
    good = _lint(
        tmp_path,
        R5_XFILE_FIXED_CALLER,
        relpath="elasticdl_tpu/worker/task_data_service.py",
        extra={
            "elasticdl_tpu/worker/ack_ledger.py": R5_XFILE_FIXED_CALLEE
        },
    )
    assert not good


def test_r5_cross_file_typed_field_method(tmp_path):
    """A blocking method reached through a constructor-typed field
    (self._ledger = AckLedger(...)) is followed into the other file."""
    bad = _lint(
        tmp_path,
        "import threading\n"
        "from elasticdl_tpu.worker.ack_ledger import AckLedger\n"
        "class Service:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._ledger = AckLedger()\n"
        "    def step(self):\n"
        "        with self._lock:\n"
        "            self._ledger.drain()\n",
        relpath="elasticdl_tpu/worker/service.py",
        extra={
            "elasticdl_tpu/worker/ack_ledger.py": (
                "import time\n"
                "class AckLedger:\n"
                "    def drain(self):\n"
                "        time.sleep(0.5)\n"
            )
        },
    )
    assert _rules_of(bad) == ["R5"], bad
    assert "sleep" in bad[0].message


# ---------------------------------------------------------------------------
# R8 — static lockset race detector
# ---------------------------------------------------------------------------

# two-thread/no-lock: the drain thread and the owner surface both touch
# self._total with no lock anywhere — and because there is NO lock, the
# runtime lock-order sanitizer (which only sees acquisition orderings a
# test actually executes) structurally cannot flag it
R8_RACE = """
import threading


class Acc:
    def __init__(self):
        self._total = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        while not self._stop.is_set():
            self._total += 1

    def snapshot(self):
        return self._total + 0

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._total = self.snapshot()
"""

R8_LOCKED = """
import threading


class Acc:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        while not self._stop.is_set():
            with self._lock:
                self._total += 1

    def snapshot(self):
        with self._lock:
            return self._total + 0

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        with self._lock:
            self._total = 0
"""


def test_r8_two_thread_no_lock_race(tmp_path):
    bad = _lint(
        tmp_path, R8_RACE, relpath="elasticdl_tpu/worker/fixture.py"
    )
    assert _rules_of(bad) == ["R8"], bad
    assert "_total" in bad[0].message
    good = _lint(
        tmp_path, R8_LOCKED, relpath="elasticdl_tpu/worker/fixture.py"
    )
    assert not good


def test_r8_exceeds_the_runtime_sanitizer(tmp_path):
    """Acceptance bar: a race the locktraced suites do NOT flag is
    caught statically. The fixture is executed for real under the
    installed sanitizer — it has no locks, so lock-order tracing sees
    nothing and raises nothing — then the same source is scanned and R8
    flags it."""
    from elasticdl_tpu.tools import locktrace

    was_enabled = locktrace.enabled()
    if not was_enabled:
        locktrace.install()
    try:
        namespace = {}
        exec(compile(R8_RACE, "r8_fixture.py", "exec"), namespace)
        acc = namespace["Acc"]()
        for _ in range(200):
            acc.snapshot()
        acc.close()  # no LockOrderError, no sanitizer finding: racy
        # code with NO locks is invisible to runtime lock tracing
    finally:
        if not was_enabled:
            locktrace.uninstall()
    bad = _lint(
        tmp_path, R8_RACE, relpath="elasticdl_tpu/worker/fixture.py"
    )
    assert _rules_of(bad) == ["R8"], (
        "the static lockset rule must catch the race the sanitizer "
        "structurally cannot"
    )


def test_r8_servicer_methods_are_concurrent_roots(tmp_path):
    """gRPC servicer methods run on the server's thread pool: two
    rpc_methods()-exposed handlers mutating shared state without a lock
    race even though the class spawns no thread itself."""
    bad = _lint(
        tmp_path,
        "class Servicer:\n"
        "    def __init__(self):\n"
        "        self._versions = {}\n"
        "    def rpc_methods(self):\n"
        "        return {\n"
        "            'report': self.report,\n"
        "            'fetch': self.fetch,\n"
        "        }\n"
        "    def report(self, req):\n"
        "        self._versions[req['k']] = req['v']\n"
        "        return {}\n"
        "    def fetch(self, req):\n"
        "        return {'v': self._versions}\n",
        relpath="elasticdl_tpu/ps/fixture.py",
    )
    assert _rules_of(bad) == ["R8"], bad
    good = _lint(
        tmp_path,
        "import threading\n"
        "class Servicer:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._versions = {}\n"
        "    def rpc_methods(self):\n"
        "        return {\n"
        "            'report': self.report,\n"
        "            'fetch': self.fetch,\n"
        "        }\n"
        "    def report(self, req):\n"
        "        with self._lock:\n"
        "            self._versions[req['k']] = req['v']\n"
        "        return {}\n"
        "    def fetch(self, req):\n"
        "        with self._lock:\n"
        "            return {'v': dict(self._versions)}\n",
        relpath="elasticdl_tpu/ps/fixture.py",
    )
    assert not good


def test_r8_exemptions_flag_publish_and_init_only(tmp_path):
    """Constant-only writes (cancel-flag publishes, GIL-atomic) and
    fields only written in __init__ are not races."""
    good = _lint(
        tmp_path,
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._config = {'a': 1}\n"  # init-only write
        "        self._cancel = False\n"
        "        self._thread = threading.Thread(\n"
        "            target=self._loop, daemon=True)\n"
        "        self._thread.start()\n"
        "    def _loop(self):\n"
        "        while not self._cancel:\n"
        "            _ = self._config\n"
        "    def close(self):\n"
        "        self._cancel = True\n"  # constant publish
        "        self._thread.join(timeout=5.0)\n",
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert not good


def test_r8_lockset_travels_across_calls(tmp_path):
    """An access in a helper is protected when every path to it holds
    the lock — the lockset composes through the call graph instead of
    stopping at the function boundary."""
    good = _lint(
        tmp_path,
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "        self._t = threading.Thread(\n"
        "            target=self._loop, daemon=True)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n"
        "    def _bump(self):\n"
        "        self._n += 1\n"  # no lexical lock HERE, but every
        "    def read(self):\n"  # caller path holds it
        "        with self._lock:\n"
        "            return self._n\n"
        "    def close(self):\n"
        "        self._t.join(timeout=5.0)\n",
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert not good
    # drop the caller's lock and the same helper write races
    bad = _lint(
        tmp_path,
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "        self._t = threading.Thread(\n"
        "            target=self._loop, daemon=True)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        self._bump()\n"
        "    def _bump(self):\n"
        "        self._n += 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._n\n"
        "    def close(self):\n"
        "        self._t.join(timeout=5.0)\n",
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert _rules_of(bad) == ["R8"], bad


def test_r8_repeated_thread_target_races_itself(tmp_path):
    """A Thread target races its OWN siblings: single-spawn is
    unprovable statically (the spawn method may run once per worker,
    like LocalInstanceManager's per-process watchers), so an unlocked
    check-then-increment reachable only from that one root is still a
    race — the lost update over-spends the budget it guards."""
    bad = _lint(
        tmp_path,
        "import threading\n"
        "class M:\n"
        "    def __init__(self):\n"
        "        self._cancel = threading.Event()\n"
        "        self._budget = 0\n"
        "    def spawn(self, proc):\n"
        "        threading.Thread(\n"
        "            target=self._watch, args=(proc,), daemon=True\n"
        "        ).start()\n"
        "    def _watch(self, proc):\n"
        "        proc.wait()\n"
        "        if self._budget < 3:\n"
        "            self._budget += 1\n"
        "    def stop(self):\n"
        "        self._cancel.set()\n",
        relpath="elasticdl_tpu/master/fixture.py",
    )
    assert _rules_of(bad) == ["R8"], bad
    assert "_budget" in bad[0].message
    good = _lint(
        tmp_path,
        "import threading\n"
        "class M:\n"
        "    def __init__(self):\n"
        "        self._cancel = threading.Event()\n"
        "        self._lock = threading.Lock()\n"
        "        self._budget = 0\n"
        "    def spawn(self, proc):\n"
        "        threading.Thread(\n"
        "            target=self._watch, args=(proc,), daemon=True\n"
        "        ).start()\n"
        "    def _watch(self, proc):\n"
        "        proc.wait()\n"
        "        with self._lock:\n"
        "            if self._budget < 3:\n"
        "                self._budget += 1\n"
        "    def stop(self):\n"
        "        self._cancel.set()\n",
        relpath="elasticdl_tpu/master/fixture.py",
    )
    assert not good


def test_r5_chain_cache_survives_call_cycles(tmp_path):
    """A mutually-recursive pair must not poison the whole-program
    chain cache: when a() <-> b() and a() also reaches a blocking sink,
    querying a first (as the earlier call site does) once cached b as
    proven-non-blocking — its only callee sat on the DFS stack, hiding
    a's other branches — and the later with-lock call of b was silently
    missed, making findings depend on scan order. Both sites must
    flag."""
    bad = _lint(
        tmp_path,
        "import threading\n"
        "from elasticdl_tpu.worker.helpers import a, b\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def use_a(self):\n"
        "        with self._lock:\n"
        "            a()\n"
        "    def use_b(self):\n"
        "        with self._lock:\n"
        "            b()\n",
        relpath="elasticdl_tpu/worker/svc.py",
        extra={
            "elasticdl_tpu/worker/helpers.py": (
                "import time\n"
                "def a():\n"
                "    b()\n"
                "    d()\n"
                "def b():\n"
                "    a()\n"
                "def d():\n"
                "    time.sleep(0.5)\n"
            )
        },
    )
    assert _rules_of(bad) == ["R5"], bad
    assert len(bad) == 2, (
        "both with-lock call sites must flag, not just the one whose "
        "query ran before the cycle poisoned the cache: %r" % bad
    )


# ---------------------------------------------------------------------------
# R9 — RPC retry-safety (the PR-2 invariants)
# ---------------------------------------------------------------------------

R9_RETRIED_PUSH = """
from elasticdl_tpu.rpc.core import Client


class BoundPS:
    def __init__(self, addr):
        self._client = Client(addr, deadline_s=5.0, retries=2)

    def push_gradient(self, grads):
        # pre-PR-2-invariant shape: the non-idempotent push rides the
        # default UNAVAILABLE retry — a resend after a post-apply
        # connection drop applies the gradient twice
        return self._client.call("push_gradient", grads=grads)
"""

R9_GUARDED = """
from elasticdl_tpu.rpc.core import Client


class MasterClient:
    def __init__(self, addr):
        self._client = Client(addr)

    def get_task(self, worker_id):
        return self._client.call("get_task", worker_id=worker_id)


class BoundPS:
    def __init__(self, addr):
        self._client = Client(addr, deadline_s=5.0, retries=2)

    def push_gradient(self, grads):
        return self._client.call(
            "push_gradient", _retriable=False, grads=grads
        )

    def dispatch(self, method, req):
        # the shipped dynamic-dispatch idiom (worker/ps_client.BoundPS)
        return self._client.call(
            method, _retriable=(method != "push_gradient"), **req
        )
"""


def test_r9_pins_retried_nonidempotent_push(tmp_path):
    bad = _lint(
        tmp_path, R9_RETRIED_PUSH, relpath="elasticdl_tpu/worker/ps.py"
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "push_gradient" in bad[0].message
    assert not _lint(
        tmp_path, R9_GUARDED, relpath="elasticdl_tpu/worker/ps.py"
    )


def test_r9_dynamic_dispatch_requires_guard(tmp_path):
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class BoundPS:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, retries=2)\n"
        "    def dispatch(self, method, req):\n"
        "        return self._client.call(method, **req)\n",
        relpath="elasticdl_tpu/worker/ps.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "guard" in bad[0].message


def test_r9_guard_must_name_the_dispatched_method(tmp_path):
    """A _retriable comparison on some OTHER variable proves nothing
    about the dispatched method. When the first .call arg is not a bare
    Name the guard cannot be tied to it — must stay a finding (an
    unrelated ``mode != "push_gradient"`` once slipped through)."""
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class BoundPS:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, retries=2)\n"
        "    def _method(self):\n"
        "        return 'push_gradient'\n"
        "    def dispatch(self, mode, req):\n"
        "        return self._client.call(\n"
        "            self._method(),\n"
        "            _retriable=(mode != 'push_gradient'),\n"
        "            **req,\n"
        "        )\n",
        relpath="elasticdl_tpu/worker/ps.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    # guarding a Name that is NOT the dispatched method is just as bad
    also_bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class BoundPS:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, retries=2)\n"
        "    def dispatch(self, method, mode, req):\n"
        "        return self._client.call(\n"
        "            method,\n"
        "            _retriable=(mode != 'push_gradient'),\n"
        "            **req,\n"
        "        )\n",
        relpath="elasticdl_tpu/worker/ps.py",
    )
    assert _rules_of(also_bad) == ["R9"], also_bad


def test_r9_master_channel_stays_blocking(tmp_path):
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class MasterClient:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=60.0)\n"
        "    def get_task(self, worker_id):\n"
        "        return self._client.call('get_task', worker_id=worker_id)\n",
        relpath="elasticdl_tpu/master/fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "blocking" in bad[0].message
    # the same ctor args on a NON-master (PS data plane) client are the
    # PR-2 design
    good = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class BoundPS:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=60.0)\n"
        "    def pull_dense(self, req):\n"
        "        return self._client.call('pull_dense', **req)\n",
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert not good


def test_r9_failover_wrapper_is_the_one_master_exemption(tmp_path):
    """Master recovery plane invariant update (docs/master_recovery.md):
    deadline/retries on the master channel are allowed ONLY inside the
    audited failover-mode wrapper (rpc/failover.MasterFailoverChannel);
    any OTHER Master* class carrying them still regresses the blocking
    control-plane contract."""
    wrapper_src = (
        "from elasticdl_tpu.rpc.core import Client\n"
        "class MasterFailoverChannel:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=30.0)\n"
        "    def call(self, rpc_name, **fields):\n"
        "        return self._client.call(\n"
        "            rpc_name,\n"
        "            _retriable=(rpc_name != 'push_gradient'),\n"
        "            **fields,\n"
        "        )\n"
    )
    good = _lint(
        tmp_path, wrapper_src, relpath="elasticdl_tpu/rpc/failover.py"
    )
    assert not good
    # the exemption is pinned to the wrapper's HOME MODULE: a
    # same-named clone anywhere else must not inherit the audit
    clone = _lint(
        tmp_path,
        wrapper_src,
        relpath="elasticdl_tpu/worker/failover_clone.py",
    )
    assert _rules_of(clone) == ["R9"], clone
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class MasterRetryingClient:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=30.0, retries=4)\n"
        "    def get_task(self, worker_id):\n"
        "        return self._client.call('get_task', worker_id=worker_id)\n",
        relpath="elasticdl_tpu/master/fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "failover" in bad[0].message


def test_r9_master_status_probe_classified(tmp_path):
    """The recovery-plane probe is idempotent by classification —
    relaunch probes and the chaos harness poll it freely; an
    UNclassified new probe name stays a finding."""
    good = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class ChaosPoller:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=2.0, retries=2)\n"
        "    def probe(self):\n"
        "        return self._client.call('master_status')\n",
        relpath="elasticdl_tpu/tools/poller_fixture.py",
    )
    assert not good
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class ChaosPoller:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=2.0)\n"
        "    def probe(self):\n"
        "        return self._client.call('master_relaunch_probe')\n",
        relpath="elasticdl_tpu/tools/poller_fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "unclassified" in bad[0].message


def test_r9_comm_plane_call_sites(tmp_path):
    """The embedding-plane invariants (docs/embedding_planes.md),
    statically enforced at Client call sites: a plane's PULL is
    retriable (pull_embedding_vector is classified idempotent — a
    replayed read is harmless), its sparse PUSH is never resent (an
    async PS applies on receipt), in hybrid mode exactly like classic
    PS mode."""
    # a hand-rolled plane that retries its sparse push: flagged
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class SparsePlane:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=5.0, retries=2)\n"
        "    def pull(self, req):\n"
        "        return self._client.call('pull_embedding_vector', **req)\n"
        "    def push(self, req):\n"
        "        return self._client.call('push_gradient', **req)\n",
        relpath="elasticdl_tpu/nn/plane_fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "push_gradient" in bad[0].message
    # the shipped shape: pull retriable, push opted out
    good = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class SparsePlane:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=5.0, retries=2)\n"
        "    def pull(self, req):\n"
        "        return self._client.call('pull_embedding_vector', **req)\n"
        "    def push(self, req):\n"
        "        return self._client.call(\n"
        "            'push_gradient', _retriable=False, **req\n"
        "        )\n",
        relpath="elasticdl_tpu/nn/plane_fixture.py",
    )
    assert not good


def test_r9_recovery_plane_rpcs_classified(tmp_path):
    """ISSUE 10's recovery-plane RPCs carry explicit idempotency
    decisions: ``ps_status`` (the reconnect probe) and
    ``transport_hello`` (whose reply now carries the shard's boot
    epoch) are reads — retriable is the DESIGN (the probe targets
    shards that just died); any new restore-flavored RPC without a
    classification stays a finding."""
    good = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class ShardProbe:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=2.0, retries=2)\n"
        "    def probe(self):\n"
        "        return self._client.call('ps_status')\n"
        "    def hello(self, req):\n"
        "        return self._client.call('transport_hello', **req)\n",
        relpath="elasticdl_tpu/worker/probe_fixture.py",
    )
    assert not good
    # a hypothetical restore RPC that skipped the classification table
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class ShardProbe:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr)\n"
        "    def restore(self):\n"
        "        return self._client.call('ps_restore_state')\n",
        relpath="elasticdl_tpu/worker/probe_fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "unclassified" in bad[0].message


def test_r9_serving_plane_rpcs_classified(tmp_path):
    """ISSUE 15's serving-plane RPCs carry explicit idempotency
    decisions: ``serving_status``/``pull_embedding_delta`` (the scorer
    fleet's delta feed — pure reads the capped-backoff retry policy
    NEEDS retriable) and the scorer's own ``score``/``scorer_status``
    surface; a new serving-flavored RPC without a classification stays
    a finding."""
    good = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class DeltaFeed:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=20.0, retries=3)\n"
        "    def status(self):\n"
        "        return self._client.call('serving_status')\n"
        "    def delta(self, req):\n"
        "        return self._client.call('pull_embedding_delta', **req)\n"
        "class ScoreChannel:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr, deadline_s=5.0, retries=2)\n"
        "    def score(self, req):\n"
        "        return self._client.call('score', **req)\n"
        "    def probe(self):\n"
        "        return self._client.call('scorer_status')\n",
        relpath="elasticdl_tpu/serving/feed_fixture.py",
    )
    assert not good
    # a hypothetical serving RPC that skipped the classification table
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class DeltaFeed:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr)\n"
        "    def push(self, req):\n"
        "        return self._client.call('push_scoring_feedback', **req)\n",
        relpath="elasticdl_tpu/serving/feed_fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "unclassified" in bad[0].message


def test_r9_unclassified_rpc_is_a_finding(tmp_path):
    bad = _lint(
        tmp_path,
        "from elasticdl_tpu.rpc.core import Client\n"
        "class C:\n"
        "    def __init__(self, addr):\n"
        "        self._client = Client(addr)\n"
        "    def frob(self):\n"
        "        return self._client.call('frobnicate')\n",
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert _rules_of(bad) == ["R9"], bad
    assert "unclassified" in bad[0].message


# ---------------------------------------------------------------------------
# R10 — copy-on-wire (the PR-8 zero-copy data-plane contract)
# ---------------------------------------------------------------------------

R10_SEED_CODEC = '''
import json
import struct

import numpy as np

_MAGIC = b"EDLT"


def serialize_tensor(t):
    # the seed copy chain this rule exists to keep dead: a staging
    # ascontiguousarray, a tobytes flatten, and the b"".join
    values = np.ascontiguousarray(t.values)
    header = json.dumps({"shape": list(values.shape)}).encode()
    return b"".join([_MAGIC, struct.pack("<I", len(header)), header,
                     values.tobytes()])


def unpack_message(data):
    view = memoryview(data)
    segments = [bytes(view[8:])]
    return segments
'''

R10_SCATTER_GATHER = '''
import json
import struct

import numpy as np

_MAGIC = b"EDLT"


def serialize_tensor(t):
    header = json.dumps({"shape": list(t.values.shape)}).encode()
    buf = bytearray(8 + len(header) + t.values.nbytes)
    view = memoryview(buf)
    struct.pack_into("<4sI", view, 0, _MAGIC, len(header))
    view[8:8 + len(header)] = header
    dest = np.frombuffer(view[8 + len(header):], dtype=t.values.dtype)
    np.copyto(dest.reshape(t.values.shape), t.values, casting="unsafe")
    return buf


def unpack_message(data):
    view = memoryview(data).toreadonly()
    (hlen,) = struct.unpack_from("<I", view, 4)
    header = json.loads(bytes(view[8:8 + hlen]))  # header-sized: exempt
    return [view[8 + hlen:]], header
'''


def test_r10_pins_the_seed_copy_chain(tmp_path):
    bad = _lint(
        tmp_path, R10_SEED_CODEC, relpath="elasticdl_tpu/rpc/fixture.py"
    )
    assert _rules_of(bad) == ["R10"], bad
    messages = "\n".join(v.message for v in bad)
    assert "tobytes" in messages
    assert "ascontiguousarray" in messages
    assert "bytes(...)" in messages
    # the scatter-gather idiom (plan, one preallocation, copyto into a
    # frombuffer view, json.loads over a header-sized bytes()) is clean
    assert not _lint(
        tmp_path,
        R10_SCATTER_GATHER,
        relpath="elasticdl_tpu/rpc/fixture.py",
    )


def test_r10_is_receiver_and_scope_typed(tmp_path):
    # .astype on a HELD array in a data-plane method copies a payload
    bad = _lint(
        tmp_path,
        "import numpy as np\n"
        "class PSClient:\n"
        "    def pull_dense(self, resp):\n"
        "        return resp.values.astype(np.float32)\n",
        relpath="elasticdl_tpu/worker/ps_client.py",
    )
    assert _rules_of(bad) == ["R10"], bad
    # chained off a fresh allocation (np.stack already copied) is not a
    # wire-payload copy; non-data-plane methods are out of scope; and
    # the same seed chain OUTSIDE the wire path is not this rule's
    # business
    assert not _lint(
        tmp_path,
        "import numpy as np\n"
        "class PSClient:\n"
        "    def pull_rows(self, rows):\n"
        "        return np.stack(rows).astype(np.float32, copy=False)\n"
        "    def _stats_blob(self, arr):\n"
        "        return bytes(arr) + arr.tobytes()\n",
        relpath="elasticdl_tpu/worker/ps_client.py",
    )
    assert not _lint(
        tmp_path,
        "import numpy as np\n"
        "def checkpoint_leaf(arr):\n"
        "    return np.ascontiguousarray(arr).tobytes()\n",
        relpath="elasticdl_tpu/common/checkpoint_utils.py",
    )


R10_HOST_STAGED_DEVICE = '''
import jax
import numpy as np


def plan_tensor_frame(t):
    # the pre-bridge shape this extension keeps dead: host-staging a
    # device payload before framing it (np.asarray pass + frame copy =
    # two walks; the bridge's frame write is the single host copy)
    values = np.asarray(t.values)
    gathered = jax.device_get(t.values)
    return values, gathered
'''

R10_DLPACK_BRIDGE = '''
import numpy as np


def is_device_array(x):
    return hasattr(x, "aval") and hasattr(x, "__dlpack__")


def write_frame(buf, off, arr):
    # the bridged idiom: plan from aval metadata, copy out of the
    # dlpack view straight into the frame — one pass, downcast fused
    if is_device_array(arr):
        arr = np.from_dlpack(arr)
    dest = np.frombuffer(buf[off:off + arr.nbytes], dtype=arr.dtype)
    np.copyto(dest.reshape(arr.shape), arr, casting="unsafe")
    return off + arr.nbytes
'''


def test_r10_flags_host_staging_of_device_arrays(tmp_path):
    # the dlpack-bridge extension: np.asarray / jax.device_get inside
    # wire scope are findings (ratcheted where genuinely host-side)
    bad = _lint(
        tmp_path,
        R10_HOST_STAGED_DEVICE,
        relpath="elasticdl_tpu/rpc/fixture.py",
    )
    assert _rules_of(bad) == ["R10"] and len(bad) == 2, bad
    messages = "\n".join(v.message for v in bad)
    assert "np.asarray" in messages
    assert "jax.device_get" in messages
    assert "dlpack" in messages
    # a dtype-normalizing asarray is the typed-decode idiom (view
    # unless the dtype differs) — not a staging copy; keyword and
    # positional dtype spellings are equivalent
    assert not _lint(
        tmp_path,
        "import numpy as np\n"
        "def pull_rows(req):\n"
        "    a = np.asarray(req['ids'], dtype=np.int64)\n"
        "    b = np.asarray(req['rows'], np.float32)\n"
        "    return a, b\n",
        relpath="elasticdl_tpu/rpc/fixture.py",
    )
    # the bridged idiom (np.from_dlpack view + copyto into the frame)
    # is clean — from_dlpack is a view, not a copy
    assert not _lint(
        tmp_path,
        R10_DLPACK_BRIDGE,
        relpath="elasticdl_tpu/rpc/fixture.py",
    )
    # outside wire scope np.asarray stays none of this rule's business
    assert not _lint(
        tmp_path,
        "import numpy as np\n"
        "def batch_leaf(x):\n"
        "    return np.asarray(x)[:1]\n",
        relpath="elasticdl_tpu/parallel/fixture.py",
    )


R10_DEVICE_HOST_ROUNDTRIP = '''
import jax
import numpy as np


class OptimizerWrapper:
    def apply_sparse_gradients(self, layer, ids, values):
        # three host round-trips the device apply plane must not grow:
        # a bare asarray staging pass, a device_get materialization,
        # and a plain .copy() of rows that should stay resident
        staged = np.asarray(values)
        drained = jax.device_get(staged)
        return drained.copy()
'''

R10_DEVICE_RESIDENT = '''
import numpy as np


class OptimizerWrapper:
    def apply_sparse_gradients(self, layer, ids, values):
        # the resident idiom: typed decode of the index vector (a view
        # unless the dtype differs), payload handed to the compiled
        # step as-is — no staging pass, no host duplicate
        idx = np.asarray(ids, dtype=np.int64)
        return self._sparse_step_jit(values, idx)

    def _stats_row_histogram(self, rows):
        # non-data-plane helpers may copy freely: the contract is
        # about payload bytes on the apply path
        return np.asarray(rows).copy()
'''


def test_r10_device_scope_flags_host_roundtrips(tmp_path):
    # the device-shard extension (docs/ps_device.md): inside the
    # push/pull/apply/gather/scatter bodies of the device store and
    # optimizer wrapper, bare np.asarray, jax.device_get AND .copy()
    # are findings — a payload must stay device-resident end to end
    bad = _lint(
        tmp_path,
        R10_DEVICE_HOST_ROUNDTRIP,
        relpath="elasticdl_tpu/ps/optimizer_wrapper.py",
    )
    assert _rules_of(bad) == ["R10"] and len(bad) == 3, bad
    messages = "\n".join(v.message for v in bad)
    assert "np.asarray" in messages
    assert "jax.device_get" in messages
    assert ".copy() host-duplicates" in messages
    # the resident idiom is clean, and out-of-plane helpers may copy
    assert not _lint(
        tmp_path,
        R10_DEVICE_RESIDENT,
        relpath="elasticdl_tpu/ps/optimizer_wrapper.py",
    )
    # the .copy() check is device-scope-only: the host PSClient data
    # plane keeps its audited-retention .copy() sites un-flagged
    assert not _lint(
        tmp_path,
        "class PSClient:\n"
        "    def push_gradient(self, t):\n"
        "        return t.values.copy()\n",
        relpath="elasticdl_tpu/worker/ps_client.py",
    )


R10_TIERED_SPILL_STAGED = '''
import numpy as np


class TieredEmbeddingTable:
    def _demote_once(self):
        # staging shapes the tier-crossing plane must not grow: a bare
        # asarray pass over the victim rows, an extra duplicate of the
        # already-owned capture, and a flatten through .tobytes()
        rows = np.asarray(self._inner.get(self._victims))
        dup = rows.copy()
        return dup.tobytes()

    def _promote(self, uniq):
        got = self._read_segment_rows(3, uniq)
        return np.asarray(got)
'''

R10_TIERED_RATCHETED_CAPTURE = '''
import numpy as np


class TieredEmbeddingTable:
    def _demote_once(self):
        # the one contract-required capture copy: the demoter must own
        # its bytes across the off-lock segment write
        return np.asarray(self._inner.get(self._victims),
                          dtype=np.float32).copy()
'''

R10_TIERED_RESIDENT = '''
import numpy as np


class TieredEmbeddingTable:
    def _promote(self, uniq):
        # the resident idiom: typed decode (a view unless the dtype
        # really differs), rows installed into warm by reference
        ids = np.asarray(uniq, dtype=np.int64)
        return self._inner.get(ids)

    def _overflow_histogram(self, rows):
        # out-of-plane helpers may copy freely: the contract is about
        # rows crossing tiers, not bookkeeping
        return np.asarray(rows).copy()
'''


def test_r10_tiered_scope_flags_tier_crossing_copies(tmp_path):
    # the tiered-store extension (docs/tiered_store.md): inside the
    # promotion/demotion bodies of ps/tiered_store.py, bare np.asarray,
    # .tobytes() AND .copy() are findings — rows move between tiers by
    # reference. The real file's ratchet budget (max 1, the demoter's
    # capture copy) absorbs exactly one, so 4 findings -> 3 violations.
    bad = _lint(
        tmp_path,
        R10_TIERED_SPILL_STAGED,
        relpath="elasticdl_tpu/ps/tiered_store.py",
    )
    assert _rules_of(bad) == ["R10"] and len(bad) == 3, bad
    # the contract-required capture copy alone fits the reason-ratchet
    assert not _lint(
        tmp_path,
        R10_TIERED_RATCHETED_CAPTURE,
        relpath="elasticdl_tpu/ps/tiered_store.py",
    )
    # the resident idiom is clean, and out-of-plane helpers may copy
    assert not _lint(
        tmp_path,
        R10_TIERED_RESIDENT,
        relpath="elasticdl_tpu/ps/tiered_store.py",
    )
    # the tiered scope is file-scoped: the same staging shapes in the
    # host EmbeddingTable (one tier, no crossing) stay un-flagged
    assert not _lint(
        tmp_path,
        R10_TIERED_SPILL_STAGED,
        relpath="elasticdl_tpu/ps/embedding_table.py",
    )


# ---------------------------------------------------------------------------
# engine mechanics: the AST cache and --json
# ---------------------------------------------------------------------------


def test_ast_cache_reparses_only_changed_files(tmp_path, monkeypatch):
    from elasticdl_tpu.tools.edlint.core import iter_source_files
    from elasticdl_tpu.tools.edlint.project import (
        _cache_path,
        load_contexts,
    )

    root = _plant(
        tmp_path,
        "import jax\n",
        "elasticdl_tpu/a.py",
        extra={"elasticdl_tpu/b.py": "import queue\n"},
    )
    # the cache must live OUTSIDE the scanned tree (it is unpickled —
    # a cache file a checkout could commit would execute code); pin
    # both the location contract and the isolation from other roots
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "xdg"))
    cache_file = _cache_path(str(root))
    assert not cache_file.startswith(str(root))
    assert cache_file.startswith(str(tmp_path / "xdg"))
    assert _cache_path(str(tmp_path)) != cache_file

    def load():
        return load_contexts(
            str(root), iter_source_files(str(root)), use_cache=True
        )

    _, _, stats = load()
    assert stats == {"hits": 0, "misses": 2}
    assert os.path.exists(cache_file)
    _, _, stats = load()
    assert stats == {"hits": 2, "misses": 0}
    # touching one file invalidates exactly that entry
    target = root / "elasticdl_tpu" / "a.py"
    target.write_text("import jax  # changed\n")
    os.utime(target, ns=(1, 1))  # force a distinct mtime_ns
    _, _, stats = load()
    assert stats == {"hits": 1, "misses": 1}
    # --no-cache semantics: nothing read, nothing written
    os.unlink(cache_file)
    _, _, stats = load_contexts(
        str(root), iter_source_files(str(root)), use_cache=False
    )
    assert stats == {"hits": 0, "misses": 2}
    assert not os.path.exists(cache_file)


def test_json_output_contract(tmp_path):
    """--json is what check.sh consumes for its compact gate summary:
    file/line/rule/message/ratchet-state per finding, stale entries,
    and the exit code mirrored in the document."""
    root = _plant(
        tmp_path,
        "import jax\n"
        "def probe():\n"
        "    return jax.devices()\n",
        "elasticdl_tpu/bad.py",
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.tools.edlint",
            "--root",
            str(root),
            "--json",
            "--stale",
        ],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=ROOT,
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["rc"] == 1
    (finding,) = [
        f for f in doc["findings"] if f["ratchet_state"] == "violation"
    ]
    assert finding["file"] == "elasticdl_tpu/bad.py"
    assert finding["line"] == 3
    assert finding["rule"] == "R1"
    assert "escapable_call" in finding["message"]
    assert doc["counts"] == [
        {"rule": "R1", "file": "elasticdl_tpu/bad.py", "count": 1}
    ]
    # stale entries: repo ratchet budgets unused in this scratch tree
    # surface here — but don't pin a specific entry, or even that any
    # exist (fixing every ratcheted site and deleting the entries is
    # the ratchet's stated end-state and must not break this test)
    for s in doc["stale"]:
        assert {"rule", "file", "budget", "used"} <= set(s)


# ---------------------------------------------------------------------------
# engine mechanics
# ---------------------------------------------------------------------------


def test_ratchet_counts_per_rule_and_file(tmp_path):
    (tmp_path / "elasticdl_tpu").mkdir()
    (tmp_path / "elasticdl_tpu" / "two.py").write_text(
        "import jax\n"
        "def a():\n"
        "    return jax.devices()\n"
        "def b():\n"
        "    return jax.devices()\n"
    )
    findings, _ = scan(str(tmp_path))
    allow = {
        "R1": {
            "elasticdl_tpu/two.py": {"max": 1, "reason": "test budget"}
        }
    }
    violations, counts, allowed = apply_ratchet(findings, allow=allow)
    assert counts[("R1", "elasticdl_tpu/two.py")] == 2
    assert len(allowed) == 1 and len(violations) == 1
    # the ratchet suppresses in line order: the SECOND site is the
    # violation, so a new site past the budget always surfaces
    assert violations[0].lineno > allowed[0].lineno


def test_stale_entries_enforce_only_shrinks(tmp_path):
    (tmp_path / "elasticdl_tpu").mkdir()
    (tmp_path / "elasticdl_tpu" / "one.py").write_text(
        "import jax\n"
        "def a():\n"
        "    return jax.devices()\n"
    )
    allow = {
        "R1": {
            "elasticdl_tpu/one.py": {"max": 3, "reason": "too wide"},
            "elasticdl_tpu/gone.py": {"max": 1, "reason": "deleted"},
        }
    }
    _, counts, _ = run(str(tmp_path), allow=allow)
    stale = stale_entries(counts, allow=allow)
    assert ("R1", "elasticdl_tpu/one.py", 1, 3) in stale
    assert ("R1", "elasticdl_tpu/gone.py", 0, 1) in stale


# ---------------------------------------------------------------------------
# R11 — static lock-order deadlock detection
# ---------------------------------------------------------------------------

R11_ABBA = """
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass
"""

R11_ORDERED = """
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._a:
            with self._b:
                pass
"""


def test_r11_same_file_abba(tmp_path):
    bad = _lint(
        tmp_path, R11_ABBA, relpath="elasticdl_tpu/worker/fixture.py"
    )
    assert _rules_of(bad) == ["R11"], bad
    msg = bad[0].message
    assert "Pair._a" in msg and "Pair._b" in msg
    # full provenance per edge: root, call chain, acquire site
    assert "root" in msg and "chain" in msg and "acquire at" in msg
    good = _lint(
        tmp_path, R11_ORDERED, relpath="elasticdl_tpu/worker/fixture.py"
    )
    assert not good


R11_XFILE_LEDGER = """
import threading
from elasticdl_tpu.worker.acks import Acks

class Ledger:
    def __init__(self):
        self._mu = threading.Lock()
        self.acks = Acks(self)

    def note(self):
        with self._mu:
            self.acks.confirm()

    def flush(self):
        with self._mu:
            pass
"""

R11_XFILE_ACKS = """
import threading

class Acks:
    def __init__(self, ledger):
        self._pending = threading.Lock()
        self._ledger = ledger

    def confirm(self):
        with self._pending:
            pass

    def requeue(self):
        with self._pending:
            self._ledger.flush()
"""


def test_r11_cross_file_abba_through_call_graph(tmp_path):
    """The ABBA only exists interprocedurally: each file on its own is
    single-lock; the inversion is Ledger.note -> Acks.confirm vs
    Acks.requeue -> Ledger.flush, with the back-reference typed from
    the ctor argument (Acks(self))."""
    bad = _lint(
        tmp_path,
        R11_XFILE_LEDGER,
        relpath="elasticdl_tpu/worker/ledger.py",
        extra={"elasticdl_tpu/worker/acks.py": R11_XFILE_ACKS},
    )
    assert _rules_of(bad) == ["R11"], bad
    msg = bad[0].message
    assert "Ledger._mu" in msg and "Acks._pending" in msg
    # each edge's chain names the cross-file hop
    assert "confirm" in msg and "flush" in msg


R11_RLOCK_REENTRANT = """
import threading

class Reent:
    def __init__(self):
        self._a = threading.RLock()
        self._b = threading.Lock()

    def outer(self):
        with self._a:
            with self._b:
                self.inner()

    def inner(self):
        with self._a:
            pass
"""


def test_r11_rlock_reentry_adds_no_edge(tmp_path):
    """inner() re-acquiring the RLock the caller already holds must NOT
    record a b->a edge (which would close a false a->b->a cycle)."""
    good = _lint(
        tmp_path,
        R11_RLOCK_REENTRANT,
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert not good, good


R11_CONDITION_ABBA = """
import threading

class CondOwner:
    def __init__(self):
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._side = threading.Lock()

    def produce(self):
        with self._mu:
            with self._side:
                pass

    def consume(self):
        with self._side:
            with self._cv:
                self._cv.notify_all()
"""

R11_CONDITION_OWNED = """
import threading

class CondOwner:
    def __init__(self):
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)

    def produce(self):
        with self._mu:
            with self._cv:
                self._cv.notify_all()

    def consume(self):
        with self._cv:
            self._cv.wait()
"""


def test_r11_condition_aliases_onto_its_lock(tmp_path):
    """Condition(self._mu) IS self._mu for ordering purposes: an ABBA
    written half through the condition is still a cycle, and acquiring
    the condition while holding its own lock is re-entry, not an
    edge."""
    bad = _lint(
        tmp_path,
        R11_CONDITION_ABBA,
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert _rules_of(bad) == ["R11"], bad
    good = _lint(
        tmp_path,
        R11_CONDITION_OWNED,
        relpath="elasticdl_tpu/worker/fixture.py",
    )
    assert not good, good


R11_THREE_LOCK = """
import threading

class Tri:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def bc(self):
        with self._b:
            with self._c:
                pass

    def ca(self):
        with self._c:
            with self._a:
                pass
"""


def test_r11_three_lock_cycle(tmp_path):
    """No single function holds an inverted pair; only the composed
    graph closes a->b->c->a."""
    bad = _lint(
        tmp_path, R11_THREE_LOCK, relpath="elasticdl_tpu/worker/fixture.py"
    )
    assert _rules_of(bad) == ["R11"], bad
    msg = bad[0].message
    assert "Tri._a" in msg and "Tri._b" in msg and "Tri._c" in msg


# ---------------------------------------------------------------------------
# --paths incremental mode + the locktrace cross-check round trip
# ---------------------------------------------------------------------------


def test_paths_scans_only_named_files_with_whole_tree_context(tmp_path):
    """--paths restricts FINDINGS to the named files while cross-file
    resolution still sees the whole tree: the R5 chain below lives in
    service.py but blocks in ack_ledger.py."""
    caller = (
        "import threading\n"
        "from elasticdl_tpu.worker.ack_ledger import AckLedger\n"
        "class Service:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._ledger = AckLedger()\n"
        "    def step(self):\n"
        "        with self._lock:\n"
        "            self._ledger.drain()\n"
    )
    callee = (
        "import time\n"
        "class AckLedger:\n"
        "    def drain(self):\n"
        "        time.sleep(0.5)\n"
        "def stray():\n"
        "    import jax\n"
        "    return jax.devices()\n"
    )
    root = _plant(
        tmp_path,
        caller,
        "elasticdl_tpu/worker/service.py",
        extra={"elasticdl_tpu/worker/ack_ledger.py": callee},
    )
    findings, broken = scan(
        str(root), only_paths=["elasticdl_tpu/worker/service.py"]
    )
    assert not broken, broken
    # the cross-file R5 surfaces; the R1 violation in the OTHER file
    # does not (it is context, not a scan target)
    assert {f.rule for f in findings} == {"R5"}, findings
    assert all(
        f.path == "elasticdl_tpu/worker/service.py" for f in findings
    )
    # a --paths target outside the scan scope is reported broken
    _, broken = scan(str(root), only_paths=["not/in/tree.py"])
    assert broken


def test_project_cache_hit_equivalence_and_invalidation(tmp_path):
    """The whole-Project pickle behind sub-second --paths runs: an
    unchanged tree must serve the cached analysis WITHOUT rebuilding
    (same findings), and any file edit must invalidate it — a stale
    Project serving yesterday's lock graph would un-sound the
    static<->dynamic cross-check."""
    import elasticdl_tpu.tools.edlint.project as proj_mod

    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "def main():\n"
        "    A().ab()\n"
    )
    root = _plant(tmp_path, src, "elasticdl_tpu/fixture.py")
    first, broken = scan(str(root))
    assert not broken, broken

    builds = []
    orig_init = proj_mod.Project.__init__

    def counting_init(self, contexts):
        builds.append(1)
        orig_init(self, contexts)

    proj_mod.Project.__init__ = counting_init
    try:
        second, _ = scan(str(root))
        assert not builds, "unchanged tree must hit the Project cache"
        assert [
            (f.path, f.lineno, f.rule) for f in second
        ] == [(f.path, f.lineno, f.rule) for f in first]
        # edit the file: the cached analysis must NOT survive, and the
        # fresh scan must see the new code (a new R5 blocking chain)
        target = root / "elasticdl_tpu/fixture.py"
        target.write_text(
            src
            + "    import time\n"
            + "    with A()._a:\n"
            + "        time.sleep(1.0)\n"
        )
        os.utime(target, ns=(1, 1))  # defeat same-ns mtime collisions
        third, _ = scan(str(root))
        assert builds, "an edited tree must rebuild the Project"
        assert any(f.rule == "R5" for f in third), third
    finally:
        proj_mod.Project.__init__ = orig_init


def test_lock_coverage_round_trip(tmp_path):
    """Dynamic edges witnessed by locktrace map back onto the static
    graph: execute a planted module under the sanitizer, export the
    edge graph, and verify coverage() finds every dynamic edge in the
    static one (the soundness direction check.sh gates on)."""
    from elasticdl_tpu.tools import locktrace
    from elasticdl_tpu.tools.edlint.core import scan_project
    from elasticdl_tpu.tools.edlint.lockgraph import coverage, load_export

    src = (
        "import threading\n"
        "class Store:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._io = threading.Lock()\n"
        "    def spill(self):\n"
        "        with self._mu:\n"
        "            with self._io:\n"
        "                pass\n"
    )
    root = _plant(tmp_path, src, "elasticdl_tpu/worker/store.py")
    planted = root / "elasticdl_tpu" / "worker" / "store.py"

    export_path = tmp_path / "edges.jsonl"
    locktrace.install()  # fresh graph; conftest never traces this module
    try:
        namespace = {}
        exec(
            compile(src, str(planted), "exec"), namespace
        )  # creation sites carry the planted path
        store = namespace["Store"]()
        store.spill()
        wrote = locktrace.export(str(export_path))
        assert wrote == 1
    finally:
        locktrace.uninstall()

    _, broken, project = scan_project(str(root))
    assert not broken, broken
    graph = project.lock_graph()
    assert graph.stats()["edges"] == 1
    cov = coverage(graph, load_export(str(export_path)))
    assert cov.dynamic_total == 1
    assert len(cov.witnessed) == 1
    assert not cov.missing, cov.missing
    assert not cov.unmatched, cov.unmatched
    assert not cov.unwitnessed
