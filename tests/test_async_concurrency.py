"""Async-mode concurrency regressions.

The 64-thread gRPC server drives MasterServicer.report_gradient from many
threads at once; in async mode each call applies its gradient immediately.
The dense optax update is a read-modify-replace of (model, opt_state), so
without serialization concurrent reports silently drop each other's whole
step (advisor finding, round 1). With plain SGD the update is
order-independent, so N reports of the same gradient must land exactly
N times.
"""

import threading

import numpy as np
import optax

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def test_async_report_gradient_loses_no_updates():
    n_threads, n_reports, lr = 8, 50, 0.01
    master = MasterServicer(
        1,
        4,
        optax.sgd(lr),
        TaskDispatcher({"s": (0, 4)}, {}, {}, 4, 1),
        use_async=True,
    )
    init = np.ones((4, 3), np.float32)
    master.report_variable({"w": init.copy()})

    barrier = threading.Barrier(n_threads)
    errors = []

    def hammer():
        try:
            barrier.wait()
            for _ in range(n_reports):
                grad = Tensor("w", np.ones((4, 3), np.float32))
                accepted, _ = master.report_gradient([grad], 0)
                assert accepted
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    total = n_threads * n_reports
    assert master.get_model_version() == total
    _, named = master.get_model(total)
    np.testing.assert_allclose(
        named["w"], init - lr * total, rtol=0, atol=1e-4
    )
