"""Real-cluster job rung (opt-in): submit through the actual CLI and
poll pod status, like the reference CI does against minikube
(reference scripts/client_test.sh + validate_job_status.sh).

Everything else in tests/test_k8s_client.py runs against fake SDKs; this
rung is the one place a real apiserver, image registry, and kubelet are
in the loop. It is gated on ``K8S_TESTS=1`` plus:

- a reachable cluster (current kubeconfig context or in-cluster SA),
- ``EDL_TEST_REGISTRY`` — a registry the cluster can pull from, used as
  ``--docker_image_repository`` (images built by docker/build_all.sh).

Run it via::

    K8S_TESTS=1 EDL_TEST_REGISTRY=registry.example/elasticdl \
        python -m pytest tests/test_k8s_job_rung.py -m slow --override-ini="addopts="
"""

import os
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not os.environ.get("K8S_TESTS"),
        reason="set K8S_TESTS=1 (and EDL_TEST_REGISTRY) with a reachable "
        "cluster to run the real-pod job rung",
    ),
]


def _sh(cmd, **kw):
    return subprocess.run(
        cmd, cwd=REPO, text=True, capture_output=True, **kw
    )


def test_cluster_train_job_reaches_succeeded():
    registry = os.environ.get("EDL_TEST_REGISTRY")
    if not registry:
        pytest.skip("EDL_TEST_REGISTRY not set")
    probe = _sh(["kubectl", "version", "--request-timeout=5s"])
    if probe.returncode != 0:
        pytest.skip("no reachable cluster: %s" % probe.stderr[-200:])

    job_name = "edl-rung-%d" % os.getpid()
    data_dir = tempfile.mkdtemp(prefix="edl_rung_")
    gen = _sh(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.data.recordio_gen.image_label",
            "--output_dir",
            os.path.join(data_dir, "data"),
            "--records_per_shard",
            "128",
            "--dataset",
            "synthetic-mnist",
        ]
    )
    assert gen.returncode == 0, gen.stderr

    submit = _sh(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.cli",
            "train",
            "--job_name",
            job_name,
            "--model_zoo",
            "model_zoo",
            "--model_def",
            "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size",
            "64",
            "--num_epochs",
            "1",
            "--num_workers",
            "2",
            "--use_async",
            "true",
            "--training_data",
            os.path.join(data_dir, "data"),
            "--docker_image_repository",
            registry,
        ],
        timeout=600,
    )
    assert submit.returncode == 0, submit.stderr

    validate = _sh(
        ["bash", "scripts/validate_job_status.sh", job_name, "600"],
        timeout=700,
    )
    assert validate.returncode == 0, (
        validate.stdout[-2000:] + validate.stderr[-2000:]
    )
