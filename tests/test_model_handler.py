"""Model handler layer-swap tests (reference tests/model_handler_test.py)."""

import flax.linen as nn
import jax
import numpy as np

from elasticdl_tpu.common.constants import DistributionStrategy
from elasticdl_tpu.common.model_handler import (
    DefaultModelHandler,
    ModelHandler,
    ParameterServerModelHandler,
)
from elasticdl_tpu.nn.embedding import (
    IDX_COLLECTION,
    ROWS_COLLECTION,
    Embedding as ElasticEmbedding,
)
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters


class SetupStyleModel(nn.Module):
    """Declarative model whose embedding is a swappable field."""

    embed: nn.Module = None

    def setup(self):
        self.dense = nn.Dense(2)

    def __call__(self, ids, training=False):
        return self.dense(self.embed(ids).sum(axis=1))


def test_factory():
    assert isinstance(
        ModelHandler.get_model_handler(
            DistributionStrategy.PARAMETER_SERVER
        ),
        ParameterServerModelHandler,
    )
    assert isinstance(
        ModelHandler.get_model_handler(DistributionStrategy.ALLREDUCE),
        DefaultModelHandler,
    )


def test_swap_embed_to_elastic():
    model = SetupStyleModel(embed=nn.Embed(100, 8, name="emb"))
    handler = ParameterServerModelHandler()
    trained = handler.get_model_to_train(model)
    assert isinstance(trained.embed, ElasticEmbedding)
    assert trained.embed.output_dim == 8
    assert trained.embed.name == "emb"


def test_export_swaps_back_with_trained_rows():
    store = Parameters()
    store.init_embedding_params([EmbeddingTableInfo("emb", 4)])
    store.embedding_params["emb"].set(
        [0, 3], np.array([[1, 1, 1, 1], [3, 3, 3, 3]], np.float32)
    )
    model = SetupStyleModel(
        embed=ElasticEmbedding(output_dim=4, name="emb")
    )
    handler = ParameterServerModelHandler()
    params = {}
    exported, params = handler.get_model_to_export(model, params, store)
    assert isinstance(exported.embed, nn.Embed)
    assert exported.embed.num_embeddings == 4
    np.testing.assert_array_equal(params["emb"]["embedding"][3], 3.0)
    np.testing.assert_array_equal(params["emb"]["embedding"][1], 0.0)


def test_default_handler_passthrough():
    model = SetupStyleModel(embed=nn.Embed(10, 2))
    handler = DefaultModelHandler()
    assert handler.get_model_to_train(model) is model
