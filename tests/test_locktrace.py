"""The runtime lock-order sanitizer (elasticdl_tpu/tools/locktrace.py).

The load-bearing pair: the ABBA interleaving is a REAL deadlock with
raw locks (both arms time out acquiring their second lock), and the
SAME interleaving under the sanitizer becomes exactly one
deterministic :class:`LockOrderError` raised at acquire time — no
thread left blocked. Plus: a three-lock cycle built sequentially by a
single thread (potential deadlocks are flagged, not just realized
ones), the reentrant-RLock false-positive guard, and the
Condition-protocol compatibility of the traced RLock.
"""

import threading
import time

import pytest

from elasticdl_tpu.tools import locktrace
from elasticdl_tpu.tools.locktrace import LockOrderError


def test_dead_lock_identity_is_never_recycled(tmp_path, traced):
    """The graph keys locks by a never-reused serial, not id().

    The chaos drills free whole components mid-test and CPython
    promptly hands the dead locks' addresses to new ones; an id-keyed
    graph would re-label the dead lock's edges with the newcomer's
    name/site at export — a phantom edge `edlint --lock-coverage`
    then flags as static-graph unsoundness."""
    import gc
    import json as _json

    a, b = locktrace.Lock("doomed_outer"), locktrace.Lock("inner")
    with a:
        with b:
            pass
    dead_uid, dead_id = a.uid, id(a)
    del a
    gc.collect()
    # hammer the allocator: one of these very likely lands on the
    # dead lock's address (the bug trigger); the assertion below must
    # hold either way, so no collision-dependence in the test
    impostors = [locktrace.Lock("impostor_%d" % i) for i in range(64)]
    for imp in impostors:
        assert imp.uid != dead_uid  # serials never recycle
        with imp:
            pass
    recycled = any(id(imp) == dead_id for imp in impostors)
    out = tmp_path / "edges.jsonl"
    assert locktrace.export(str(out)) >= 1
    edges = [_json.loads(l) for l in out.read_text().splitlines()]
    doomed = [e for e in edges if e["dst"] == "inner"]
    assert len(doomed) == 1 and doomed[0]["src"] == "doomed_outer", (
        "dead lock's edge was re-labeled (id recycled: %s): %r"
        % (recycled, doomed)
    )
    assert not any(e["src"].startswith("impostor") for e in edges)


@pytest.fixture
def traced():
    """Tracing on for the test body, always restored."""
    locktrace.install()
    try:
        yield
    finally:
        locktrace.uninstall()


def _run_abba(lock_a, lock_b, second_timeout=None, join_timeout=10.0):
    """Drive the canonical ABBA interleaving to its crossing point.

    Each arm takes its first lock, proves it via an event, waits for
    the OTHER arm's proof, then goes for its second lock — so both
    arms are guaranteed to be holding one lock and wanting the other
    at the same moment. On the bounded path each arm additionally
    announces when its second acquire has CONCLUDED and waits for the
    other's announcement before releasing its first lock — without
    that, the two ~second_timeout windows race and a photo-finish
    release lets one arm sneak its second acquire in. Returns
    (second-acquire outcomes, order errors, threads)."""
    e1, e2 = threading.Event(), threading.Event()
    d1, d2 = threading.Event(), threading.Event()
    results, errors = [], []

    def arm(first, second, mine, theirs, my_done, their_done, label):
        try:
            with first:
                mine.set()
                theirs.wait(5.0)
                if second_timeout is not None:
                    try:
                        got = second.acquire(timeout=second_timeout)
                    finally:
                        # set even when the sanitizer raises, so the
                        # other arm never waits out its full guard
                        my_done.set()
                    if got:
                        second.release()
                    else:
                        their_done.wait(5.0)
                    results.append((label, got))
                else:
                    with second:
                        results.append((label, True))
        except LockOrderError as err:
            errors.append((label, err))

    threads = [
        threading.Thread(
            target=arm,
            args=(lock_a, lock_b, e1, e2, d1, d2, "t1"),
            daemon=True,
        ),
        threading.Thread(
            target=arm,
            args=(lock_b, lock_a, e2, e1, d2, d1, "t2"),
            daemon=True,
        ),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(join_timeout)
    return results, errors, threads


def test_abba_repro_deadlocks_raw_but_raises_under_locktrace():
    """THE acceptance repro, driven through plain ``threading.Lock()``.

    Without ``EDL_LOCKTRACE=1`` the interleaving is a genuine deadlock
    — both arms sit holding one lock wanting the other until the
    bounded second acquire gives up (remove the timeout and the test
    hangs forever). With ``EDL_LOCKTRACE=1`` the conftest fixture has
    installed the sanitizer for this suite, so the SAME code — no
    edits — gets exactly one deterministic LockOrderError at acquire
    time and the other arm completes."""
    t0 = time.monotonic()
    results, errors, threads = _run_abba(
        threading.Lock(), threading.Lock(), second_timeout=1.0
    )
    assert not any(t.is_alive() for t in threads)
    if locktrace.enabled():
        assert len(errors) == 1, errors
        assert [got for _, got in results] == [True]
        assert "lock-order inversion" in str(errors[0][1])
    else:
        assert not errors
        assert sorted(results) == [("t1", False), ("t2", False)], (
            "expected both arms to time out on their second lock "
            "(the ABBA deadlock), got %r" % (results,)
        )
        assert time.monotonic() - t0 >= 1.0  # they truly waited it out


def test_abba_becomes_one_deterministic_raise(traced):
    """Same interleaving, traced locks, UNBOUNDED second acquire: the
    second thread to cross gets LockOrderError before blocking, its
    first lock releases on unwind, and the other arm completes — no
    deadlock, no timeout discipline needed."""
    results, errors, threads = _run_abba(
        locktrace.Lock("A"), locktrace.Lock("B")
    )
    assert not any(t.is_alive() for t in threads), (
        "sanitized ABBA must not hang"
    )
    assert len(errors) == 1, errors
    assert len(results) == 1, results
    msg = str(errors[0][1])
    assert "lock-order inversion" in msg
    assert "A" in msg and "B" in msg


def test_three_lock_cycle_is_flagged_sequentially(traced):
    """A -> B, B -> C, then C -> A closes the cycle. One thread, never
    actually blocked: the sanitizer flags POTENTIAL deadlocks from the
    cumulative graph, not just realized interleavings."""
    a, b, c = (
        locktrace.Lock("A"),
        locktrace.Lock("B"),
        locktrace.Lock("C"),
    )
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(LockOrderError) as err:
            with a:
                pass
    assert "A -> B -> C" in str(err.value)


def test_reentrant_rlock_is_not_a_false_positive(traced):
    r = locktrace.RLock("R")
    with r:
        with r:
            with r:
                pass
    # and a repeated consistent order stays silent
    m = locktrace.Lock("M")
    for _ in range(2):
        with r:
            with m:
                pass


def test_traced_rlock_supports_condition_protocol(traced):
    cond = threading.Condition(locktrace.RLock("cond-lock"))
    box = []

    def consumer():
        with cond:
            while not box:
                if not cond.wait(timeout=5.0):
                    return
            box.append("seen")

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    time.sleep(0.05)
    with cond:
        box.append("item")
        cond.notify()
    t.join(5.0)
    assert not t.is_alive()
    assert box == ["item", "seen"]


def test_basic_lock_semantics_preserved(traced):
    lk = locktrace.Lock("plain")
    assert lk.acquire(timeout=1.0)
    assert lk.locked()
    lk.release()
    assert not lk.locked()
    with lk:
        assert lk.locked()
    # non-blocking try-acquire bypasses the graph (cannot deadlock)
    assert lk.acquire(blocking=False)
    lk.release()


def test_uninstall_restores_real_constructors():
    locktrace.install()
    locktrace.uninstall()
    assert threading.Lock is not None
    lk = threading.Lock()
    assert not isinstance(lk, locktrace.TracedLock)


def test_edge_graph_resets_per_install():
    """The acquisition graph dies with uninstall(): an A->B ordering
    witnessed in one install session must NOT survive into the next —
    a stale edge would turn the next session's innocent B->A into a
    phantom inversion (and corrupt the exported graph the static
    cross-check validates against)."""
    locktrace.install()
    try:
        a, b = locktrace.Lock("A"), locktrace.Lock("B")
        with a:
            with b:
                pass
        assert len(locktrace.export_edges()) == 1
    finally:
        locktrace.uninstall()
    assert locktrace.export_edges() == []  # graph died with the tracer
    locktrace.install()
    try:
        assert locktrace.export_edges() == []  # fresh graph
        # the REVERSED order is fine now: no stale A->B edge to close
        # a cycle against
        with b:
            with a:
                pass
        edges = locktrace.export_edges()
        assert [(e["src"], e["dst"]) for e in edges] == [("B", "A")]
    finally:
        locktrace.uninstall()


def test_export_writes_jsonl_with_creation_sites(tmp_path, traced):
    """export() appends one JSON object per witnessed edge, carrying
    the FULL creation sites ``edlint --lock-coverage`` maps onto
    static lock identities."""
    import json as _json

    a, b = locktrace.Lock("outer"), locktrace.Lock("inner")
    with a:
        with b:
            pass
    out = tmp_path / "edges.jsonl"
    assert locktrace.export(str(out)) == 1
    assert locktrace.export(str(out)) == 1  # append mode: runs stack
    lines = out.read_text().splitlines()
    assert len(lines) == 2
    for line in lines:
        edge = _json.loads(line)
        assert edge["src"] == "outer" and edge["dst"] == "inner"
        # full paths, not basenames: the cross-check's join key
        for site in (edge["src_site"], edge["dst_site"]):
            path, _, line = site.rpartition(":")
            assert path.endswith("test_locktrace.py") and path != (
                "test_locktrace.py"
            ), site
            assert int(line) > 0
