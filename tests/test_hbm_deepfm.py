"""HBM-sharded DeepFM as a training strategy (VERDICT r1 item 3 /
BASELINE.json north star): tables row-sharded over mesh HBM, all_to_all
row routing, sparse update inside the jitted step, checkpointed through
the params pytree.

Equivalence target: the host-PS elastic-embedding plane applies row-sparse
optax updates that are exactly dense-SGD-on-touched-rows
(tests/test_ps_store.py proves store==dense per step), so HBM-sharded
training is validated against the same dense reference: an unsharded
``jnp.take`` DeepFM trained on the identical batch stream must produce
the same tables.
"""

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.training.step import TrainState, make_train_step
from elasticdl_tpu.worker.allreduce_worker import AllReduceWorker
from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo
from tests.in_process_master import InProcessMaster
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file

VOCAB = 96


def _batches(n_steps, batch=16, length=10, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_steps):
        ids = rng.integers(0, VOCAB, size=(batch, length))
        labels = rng.integers(0, 2, size=(batch, 1)).astype(np.int64)
        out.append(({"feature": ids.astype(np.int64)}, labels))
    return out


def _train(model, batches, params, state):
    opt = optax.sgd(0.05)
    ts = TrainState.create(params, state, opt)
    step = make_train_step(model, zoo.loss, opt)
    key = jax.random.PRNGKey(0)
    for features, labels in batches:
        ts, _ = step(ts, features, labels, key)
    return jax.tree_util.tree_map(np.asarray, ts.params)


def test_hbm_deepfm_matches_dense_training():
    """10 jitted steps, tables sharded over the 8-device mesh with a2a
    routing == the same model with a plain dense take."""
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    batches = _batches(10)

    dense_model = zoo.DeepFMEdl(
        embedding_dim=8, fc_unit=8, vocab_size=VOCAB, force_hbm=True
    )
    hbm_model = zoo.build_distributed_model(
        mesh, embedding_dim=8, fc_unit=8, vocab_size=VOCAB
    )
    assert hbm_model.mesh is mesh

    variables = init_variables(
        hbm_model, jax.random.PRNGKey(0), batches[0][0]
    )
    params, state = split_variables(variables)
    # identical init for the dense twin: same param tree applies (both
    # are HbmEmbedding under different lookup paths)
    dense_variables = init_variables(
        dense_model, jax.random.PRNGKey(0), batches[0][0]
    )
    dense_params, dense_state = split_variables(dense_variables)
    for a, b in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(dense_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # place sharded per the zoo's param_shardings hook (specs may be
    # PadDim0-wrapped; collect_sharded_paths unwraps)
    from elasticdl_tpu.parallel.elastic import collect_sharded_paths

    specs = collect_sharded_paths(zoo.param_shardings(mesh))
    placed = jax.tree_util.tree_map(jax.device_put, params)
    for layer in ("embedding", "id_bias"):
        placed[layer]["table"] = jax.device_put(
            params[layer]["table"],
            NamedSharding(mesh, specs[(layer, "table")]),
        )

    with mesh:
        got = _train(hbm_model, batches, placed, state)
    want = _train(dense_model, batches, dense_params, dense_state)
    for (path_a, a), (path_b, b) in zip(
        jax.tree_util.tree_flatten_with_path(got)[0],
        jax.tree_util.tree_flatten_with_path(want)[0],
    ):
        assert path_a == path_b
        np.testing.assert_allclose(
            a, b, rtol=2e-4, atol=2e-5, err_msg=str(path_a)
        )


def test_hbm_table_gradient_stays_sharded():
    """The table gradient must carry the table's sharding — no device
    ever holds the dense (V, D) gradient."""
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    model = zoo.build_distributed_model(
        mesh, embedding_dim=8, fc_unit=8, vocab_size=VOCAB
    )
    batch = _batches(1)
    features, labels = batch[0]
    variables = init_variables(model, jax.random.PRNGKey(0), features)
    params, state = split_variables(variables)
    spec = NamedSharding(mesh, P("data", None))
    params["embedding"]["table"] = jax.device_put(
        np.asarray(params["embedding"]["table"]), spec
    )
    params["id_bias"]["table"] = jax.device_put(
        np.asarray(params["id_bias"]["table"]), spec
    )

    @jax.jit
    def grads_of(p):
        def loss_fn(pp):
            out = model.apply(
                {"params": pp, **state}, features, training=True
            )
            return zoo.loss(out, labels)

        return jax.grad(loss_fn)(p)

    with mesh:
        g = grads_of(params)
    g_table = g["embedding"]["table"]
    assert g_table.sharding.is_equivalent_to(spec, g_table.ndim)
    # each device's shard is (V/8, D) — the dense (V, D) grad never
    # materializes on any single device
    shard_shapes = {s.data.shape for s in g_table.addressable_shards}
    assert shard_shapes == {(VOCAB // 8, 8)}


def test_allreduce_worker_trains_hbm_deepfm_e2e():
    """Full task-driven job through AllReduceWorker: the zoo hooks swap
    in the HBM model, tables shard, job completes, checkpoint-able host
    state comes back through the params pytree."""
    f = create_recordio_file(128, DatasetName.FRAPPE, 10)
    task_d = TaskDispatcher({f: (0, 128)}, {}, {}, 64, 1)
    master = MasterServicer(
        1,
        16,
        None,
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    worker = AllReduceWorker(
        worker_id=0,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def=(
            "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
        ),
        model_params="embedding_dim=8,fc_unit=8",
        stub=InProcessMaster(master),
    )
    losses = worker.run()
    assert task_d.finished()
    assert losses and all(np.isfinite(losses))
    # the distributed hooks took effect: tables are mesh-sharded params
    ts = worker.trainer.train_state
    table = ts.params["embedding"]["table"]
    assert len(table.sharding.device_set) == 8
    assert table.shape[0] == zoo.VOCAB_SIZE
    # host state (the checkpoint source) round-trips the sharded table
    host = worker.trainer.get_host_state()
    assert np.asarray(host.params["embedding"]["table"]).shape == (
        zoo.VOCAB_SIZE,
        8,
    )
