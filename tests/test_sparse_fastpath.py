"""Sparse-comms fast path: dedup/combine/cache correctness.

The fast path (docs/sparse_fast_path.md) must be a pure wire
optimization: identical forward activations and identical gradients to
the naive per-occurrence path on any batch, including heavy id
duplication, non-divisor (PadDim0-style padded) vocabs, and
mask_zero/combiner layer variants. These tests pin that equivalence on
both embedding planes, plus the HotRowCache's LRU/version semantics and
the satellite fixes (prefetch sentinel cancel, stale-round ledger
append).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.mesh import create_mesh


# ---------------------------------------------------------------------------
# padded_unique
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "ids",
    [
        np.array([5, 3, 5, 5, 7, 3, 0]),
        np.array([4]),
        np.array([9, 9, 9, 9]),
        np.arange(16)[::-1].copy(),
    ],
)
def test_padded_unique_matches_np_unique(ids):
    from elasticdl_tpu.nn.sparse_comms import padded_unique

    ids = ids.astype(np.int32)
    uids, inv, k = jax.jit(padded_unique)(ids)
    expect = np.unique(ids)
    assert int(k) == len(expect)
    np.testing.assert_array_equal(np.asarray(uids)[: len(expect)], expect)
    np.testing.assert_array_equal(np.asarray(uids)[len(expect):], -1)
    # inverse reconstructs the input exactly
    np.testing.assert_array_equal(np.asarray(uids)[np.asarray(inv)], ids)


# ---------------------------------------------------------------------------
# HBM plane: dedup a2a == naive a2a == plain take (fwd + grad)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "mesh_axes", [{"data": 8}, {"data": 2, "model": 4}]
)
def test_a2a_dedup_matches_naive_forward_and_grad(mesh_axes):
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    # capacity=None: the always-correct worst case for BOTH paths (a
    # tight capacity is where they legitimately diverge — naive drops
    # per-occurrence overflow, dedup stays exact; covered below)
    capacity = None
    axis = "model" if "model" in mesh_axes else "data"
    mesh = create_mesh(mesh_axes, axis_names=tuple(mesh_axes))
    rng = np.random.default_rng(0)
    table = rng.standard_normal((64, 5)).astype(np.float32)
    # heavy duplication: 48 ids drawn from 6 distinct values
    ids = rng.choice(rng.permutation(64)[:6], size=(8, 6)).astype(np.int32)

    def lookup(t, dedup):
        return all_to_all_lookup(
            t, ids, mesh, axis, capacity=capacity, dedup=dedup
        )

    fwd_naive = np.asarray(jax.jit(lambda t: lookup(t, False))(table))
    fwd_dedup = np.asarray(jax.jit(lambda t: lookup(t, True))(table))
    np.testing.assert_allclose(fwd_dedup, table[ids], rtol=1e-6)
    np.testing.assert_allclose(fwd_dedup, fwd_naive, rtol=1e-6)

    def loss(t, dedup):
        out = lookup(t, dedup)
        return jnp.sum(out * out * jnp.arange(out.size).reshape(out.shape))

    g_naive = np.asarray(jax.jit(jax.grad(lambda t: loss(t, False)))(table))
    g_dedup = np.asarray(jax.jit(jax.grad(lambda t: loss(t, True)))(table))
    np.testing.assert_allclose(g_dedup, g_naive, rtol=1e-5, atol=1e-6)


def test_a2a_dedup_correct_at_unique_sized_capacity():
    """A capacity sized for the UNIQUE count (way below the occurrence
    count) must stay exact under dedup — the whole point of the fast
    path — while the naive path drops rows at the same capacity."""
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    table = np.arange(64, dtype=np.float32).reshape(32, 2)
    ids = np.tile(np.array([3, 17, 3, 3], np.int32), 8)  # 32 ids, 2 unique

    got = np.asarray(
        jax.jit(
            lambda t: all_to_all_lookup(
                t, ids, mesh, "data", capacity=2, dedup=True
            )
        )(table)
    )
    np.testing.assert_allclose(got, table[ids], rtol=1e-6)

    _, n_over = jax.jit(
        lambda t: all_to_all_lookup(
            t, ids, mesh, "data", capacity=2, dedup=True,
            return_overflow=True,
        )
    )(table)
    assert int(n_over) == 0


def test_a2a_dedup_on_padded_non_divisor_vocab():
    """PadDim0-style world: a prime logical vocab padded up to the next
    multiple of the axis size; ids only ever target the logical rows."""
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    logical, padded = 67, 72  # 67 is prime; 72 = next multiple of 8
    rng = np.random.default_rng(3)
    table = np.zeros((padded, 3), np.float32)
    table[:logical] = rng.standard_normal((logical, 3))
    ids = rng.choice(
        rng.permutation(logical)[:9], size=(41,)
    ).astype(np.int32)

    for dedup in (False, True):
        got = np.asarray(
            jax.jit(
                lambda t, d=dedup: all_to_all_lookup(
                    t, ids, mesh, "data", dedup=d
                )
            )(table)
        )
        np.testing.assert_allclose(got, table[ids], rtol=1e-6)

    def loss(t, dedup):
        return jnp.sum(
            all_to_all_lookup(t, ids, mesh, "data", dedup=dedup) ** 2
        )

    g0 = np.asarray(jax.jit(jax.grad(lambda t: loss(t, False)))(table))
    g1 = np.asarray(jax.jit(jax.grad(lambda t: loss(t, True)))(table))
    np.testing.assert_allclose(g1, g0, rtol=1e-5, atol=1e-6)
    assert (g1[logical:] == 0).all()  # padding rows never touched


@pytest.mark.parametrize("mask_zero", [False, True])
def test_hbm_layer_dedup_equivalence_trains(mask_zero):
    """HbmEmbedding(dedup=True) — the default — produces the same
    forward and the same table gradient as dedup=False inside a jitted
    train-style step, mask_zero included."""
    from elasticdl_tpu.nn.hbm_embedding import HbmEmbedding

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    rng = np.random.default_rng(1)
    ids = rng.choice([0, 2, 5, 9], size=(16, 4)).astype(np.int32)

    outs, grads = [], []
    for dedup in (False, True):
        model = HbmEmbedding(
            vocab_size=16, features=4, mesh=mesh, axis="data",
            method="a2a", mask_zero=mask_zero, dedup=dedup,
        )
        variables = model.init(jax.random.PRNGKey(0), ids)

        @jax.jit
        def fwd_loss(params):
            out, _ = model.apply(
                {"params": params}, ids, mutable=["metrics"]
            )
            return jnp.sum(out**2), out

        with mesh:
            (loss, out), g = jax.value_and_grad(
                fwd_loss, has_aux=True
            )(variables["params"])
        outs.append(np.asarray(out))
        grads.append(np.asarray(g["table"]))
    np.testing.assert_allclose(outs[1], outs[0], rtol=1e-6)
    np.testing.assert_allclose(grads[1], grads[0], rtol=1e-5, atol=1e-6)


def test_collective_dedup_matches_naive():
    """The elastic-plane collective body (axis bound by an outer
    shard_map, each device holding a distinct batch slice) under dedup
    matches the naive collective and the dense take."""
    from elasticdl_tpu.nn.hbm_embedding import (
        a2a_dedup_lookup_collective,
        a2a_lookup_collective,
    )
    from elasticdl_tpu.parallel.ring_attention import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    rng = np.random.default_rng(5)
    table = rng.standard_normal((64, 4)).astype(np.float32)
    ids = rng.choice(
        rng.permutation(64)[:7], size=(64,)
    ).astype(np.int32)

    def run(body):
        fn = shard_map(
            lambda t, i: body(t, i, "data"),
            mesh=mesh,
            in_specs=(P("data", None), P("data")),
            out_specs=P("data", None),
            check_rep=False,
        )
        return np.asarray(jax.jit(fn)(table, ids))

    naive = run(a2a_lookup_collective)
    dedup = run(a2a_dedup_lookup_collective)
    np.testing.assert_allclose(naive, table[ids], rtol=1e-6)
    np.testing.assert_allclose(dedup, naive, rtol=1e-6)


# ---------------------------------------------------------------------------
# PS plane: naive plan == dedup plan (fwd + row grads), combiner variants
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mask_zero", [False, True])
@pytest.mark.parametrize("combiner", [None, "sum", "mean", "sqrtn"])
def test_ps_plane_plan_equivalence(mask_zero, combiner):
    """Forward and per-unique-id row gradients through the elastic
    Embedding layer are identical between the dedup plan and the naive
    per-occurrence plan (once the naive grads are row-combined)."""
    from elasticdl_tpu.common.tensor import combine_indexed_slices
    from elasticdl_tpu.nn.embedding import (
        Embedding,
        IDX_COLLECTION,
        ROWS_COLLECTION,
        build_collection,
        call_slot_name,
        plan_lookup_multi,
    )

    rng = np.random.default_rng(2)
    ids = rng.choice([0, 3, 3, 7, 11], size=(6, 5)).astype(np.int64)
    dim = 4
    store = rng.standard_normal((16, dim)).astype(np.float32)
    layer = Embedding(
        output_dim=dim, mask_zero=mask_zero, combiner=combiner
    )

    results = {}
    for dedup in (True, False):
        unique, (idx,), bucket = plan_lookup_multi([ids], dedup=dedup)
        rows = store[unique]
        rows = np.concatenate(
            [rows, np.zeros((bucket - len(unique), dim), np.float32)]
        )
        variables = {
            ROWS_COLLECTION: build_collection({(): rows}, "rows"),
            IDX_COLLECTION: build_collection(
                {(call_slot_name(0),): idx}, "idx"
            ),
        }

        def fwd(rows_arr):
            v = dict(variables)
            v[ROWS_COLLECTION] = build_collection({(): rows_arr}, "rows")
            return layer.apply(v, ids)

        out = np.asarray(jax.jit(fwd)(rows))
        g = np.asarray(
            jax.jit(jax.grad(lambda r: jnp.sum(fwd(r) ** 2)))(rows)
        )
        # strip padding, combine to per-unique-id rows
        uid, grows = combine_indexed_slices(unique, g[: len(unique)])
        results[dedup] = (out, uid, grows)

    out_d, uid_d, g_d = results[True]
    out_n, uid_n, g_n = results[False]
    np.testing.assert_allclose(out_d, out_n, rtol=1e-6)
    np.testing.assert_array_equal(uid_d, uid_n)
    np.testing.assert_allclose(g_d, g_n, rtol=1e-5, atol=1e-6)


def test_combine_indexed_slices():
    from elasticdl_tpu.common.tensor import Tensor, combine_indexed_slices

    idx = np.array([7, 2, 7, 2, 5], np.int64)
    vals = np.arange(10, dtype=np.float32).reshape(5, 2)
    uid, combined = combine_indexed_slices(idx, vals)
    np.testing.assert_array_equal(uid, [2, 5, 7])
    np.testing.assert_allclose(
        combined, [[8.0, 10.0], [8.0, 9.0], [4.0, 6.0]]
    )

    t = Tensor("emb", vals, indices=idx).combined()
    np.testing.assert_array_equal(t.indices, uid)
    np.testing.assert_allclose(t.values, combined)
    # duplicate-free input keeps values (sorted by id), dense is a no-op
    t2 = Tensor("e", vals[:3], indices=np.array([9, 1, 4])).combined()
    np.testing.assert_array_equal(t2.indices, [1, 4, 9])
    np.testing.assert_allclose(t2.values, vals[[1, 2, 0]])
    dense = Tensor("d", vals)
    assert dense.combined() is dense


# ---------------------------------------------------------------------------
# hot-row cache
# ---------------------------------------------------------------------------


def test_hot_row_cache_lru_and_version_window():
    from elasticdl_tpu.worker.ps_client import HotRowCache

    cache = HotRowCache(2, window=1)
    r = lambda v: np.full((3,), v, np.float32)  # noqa: E731
    cache.note_version(0, 5)
    cache.put("t", 1, 0, 5, r(1))
    cache.put("t", 2, 0, 5, r(2))
    assert cache.get("t", 1) is not None
    cache.put("t", 3, 0, 5, r(3))  # evicts id 2 (id 1 was touched)
    assert cache.get("t", 2) is None
    assert cache.get("t", 1) is not None

    # within the window: version 6 seen, entries at 5 still serve
    cache.note_version(0, 6)
    assert cache.get("t", 1) is not None
    # beyond the window: entries at 5 age out
    cache.note_version(0, 7)
    assert cache.get("t", 1) is None
    # other shards' versions don't invalidate this shard's rows
    cache.put("t", 4, 1, 0, r(4))
    cache.note_version(0, 50)
    assert cache.get("t", 4) is not None


class _CountingPS:
    """In-process PS stub counting pull_embedding_vector calls."""

    def __init__(self, dim=2):
        self.version = 0
        self.dim = dim
        self.pulls = 0

    def pull_embedding_vector(self, req):
        self.pulls += 1
        ids = np.asarray(req["ids"], np.int64)
        rows = np.stack(
            [np.full((self.dim,), i + 100.0 * self.version) for i in ids]
        ).astype(np.float32)
        return {"rows": rows, "version": self.version}


def test_ps_client_hot_row_cache_serves_repeats_locally():
    from elasticdl_tpu.worker.ps_client import PSClient

    ps = [_CountingPS(), _CountingPS()]
    client = PSClient(ps, hot_row_cache_rows=64, staleness_window=1)
    ids = np.array([0, 1, 2, 3, 4, 5])
    first = client.pull_embedding_vectors("emb", ids)
    assert ps[0].pulls == 1 and ps[1].pulls == 1
    # repeat pull: every id hits, NO rpc at all
    again = client.pull_embedding_vectors("emb", ids)
    np.testing.assert_allclose(again, first)
    assert ps[0].pulls == 1 and ps[1].pulls == 1
    # shard 0 advances beyond the window: only its ids re-pull
    ps[0].version = 2
    client.pull_embedding_vectors("emb", np.array([0, 2]))  # sees v2... cached
    # the client only learns shard 0 moved when a response says so;
    # simulate a push-response version note
    client.hot_row_cache.note_version(0, 2)
    out = client.pull_embedding_vectors("emb", ids)
    assert ps[0].pulls == 2  # shard-0 misses re-pulled
    assert ps[1].pulls == 1  # shard-1 rows still fresh
    np.testing.assert_allclose(out[::2], np.asarray(first)[::2] + 200.0)


def test_ps_client_cache_correct_against_live_servicer():
    """End-to-end against the real PserverServicer: a cached client and
    an uncached client read identical rows while the table mutates,
    as long as pushes note versions (bounded staleness honored)."""
    import optax

    from elasticdl_tpu.common.tensor import Tensor
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.worker.ps_client import PSClient

    params = Parameters()
    servicer = PserverServicer(
        params, 1, optax.sgd(0.5), use_async=True
    )
    client = PSClient(
        [servicer], hot_row_cache_rows=16, staleness_window=0
    )
    client.push_model(
        {"w": np.zeros((2,), np.float32)},
        embedding_infos=[
            type("I", (), {"name": "emb", "dim": 2, "initializer": "zeros"})
        ],
    )
    ids = np.array([1, 3, 1, 5])
    rows1 = client.pull_embedding_vectors("emb", ids)
    # push a sparse grad through the CLIENT (so it notes the version)
    grad = Tensor(
        "emb", np.ones((4, 2), np.float32), indices=ids
    )
    client.push_gradient({}, [grad], version=0)
    rows2 = client.pull_embedding_vectors("emb", ids)
    naive = PSClient([servicer]).pull_embedding_vectors("emb", ids)
    np.testing.assert_allclose(rows2, naive)
    # window=0: the post-push pull must not have served stale rows
    assert not np.allclose(rows1, rows2)


# ---------------------------------------------------------------------------
# satellites: prefetch sentinel cancel; stale-round ledger append
# ---------------------------------------------------------------------------


def test_prefetch_producer_exits_when_abandoned_at_end_of_source():
    """Abandon the consumer with the queue full right as the source
    exhausts: the producer's terminal _END put must honor the cancel
    event instead of blocking forever (ADVICE finding 1)."""
    from elasticdl_tpu.data.dataset import Dataset

    before = set(threading.enumerate())
    ds = Dataset.from_generator(lambda: iter(range(3))).prefetch(1)
    it = iter(ds)
    assert next(it) == 0
    # producer now has the queue full (1) and item 2 pending; let it
    # reach the terminal put with the queue still full, then abandon
    time.sleep(0.1)
    it.close()
    deadline = time.time() + 10
    while time.time() < deadline:
        leaked = [
            t
            for t in set(threading.enumerate()) - before
            if t.is_alive()
        ]
        if not leaked:
            return
        time.sleep(0.05)
    raise AssertionError("prefetch producer thread leaked: %s" % leaked)


def test_prefetch_exception_put_honors_cancel():
    from elasticdl_tpu.data.dataset import Dataset

    def boom():
        yield 0
        yield 1
        raise RuntimeError("source failed")

    before = set(threading.enumerate())
    it = iter(Dataset.from_generator(boom).prefetch(1))
    assert next(it) == 0
    time.sleep(0.1)
    it.close()  # exception sentinel put must also give up
    deadline = time.time() + 10
    while time.time() < deadline:
        if not [
            t
            for t in set(threading.enumerate()) - before
            if t.is_alive()
        ]:
            return
        time.sleep(0.05)
    raise AssertionError("prefetch producer leaked after source error")


def test_escapable_call_returns_raises_and_times_out():
    """The daemon-thread escapable-call machinery the graft-entry device
    probe and the elastic trainer share (parallel/elastic.py)."""
    from elasticdl_tpu.parallel.elastic import EscapeTimeout, escapable_call

    assert escapable_call(lambda: 41 + 1) == 42
    with pytest.raises(ValueError, match="boom"):
        escapable_call(lambda: (_ for _ in ()).throw(ValueError("boom")))

    t0 = time.monotonic()
    with pytest.raises(EscapeTimeout):
        escapable_call(lambda: time.sleep(30), timeout=0.3)
    assert time.monotonic() - t0 < 5  # escaped, did not wait out the sleep

    # abort probe: fires after abort_after, escapes the wedged call
    with pytest.raises(EscapeTimeout):
        escapable_call(
            lambda: time.sleep(30),
            should_abort=lambda: True,
            abort_after=0.1,
            abort_interval=0.05,
        )


def test_record_stream_round_bump_during_get_task_hands_task_back():
    """requeue_inflight landing between the producer's get_task return
    and its ledger append must NOT leave the stale task in the cleared
    ledger (ADVICE finding 2): it is reported back instead."""
    from elasticdl_tpu.worker.task_data_service import TaskDataService

    class _Task:
        def __init__(self):
            self.task_id = 42
            self.shard_name = "shard"
            self.type = "TRAINING"
            self.start, self.end = 0, 8
            self.model_version = 0

    class _Worker:
        def __init__(self):
            self.reported = []
            self.service = None

        def get_task(self, task_type=None):
            task = _Task()
            # the race window: the round is abandoned while this task
            # is in flight back to the producer
            self.service.requeue_inflight("spare park")
            return task

        def report_task_result(self, task_id, err_msg="", exec_counters=None):
            self.reported.append((task_id, err_msg))

    import collections

    from elasticdl_tpu.data.input_stats import InputPlaneStats

    worker = _Worker()
    service = TaskDataService.__new__(TaskDataService)
    service._worker = worker
    service._ledger_lock = threading.Lock()
    service._stream_open = True
    service._parked_export_task = None
    service._clear_ledger()
    service._primed_task = None
    service._metadata_primed = True
    service._round_id = 0
    service._task_prefetch = 0
    service._fetcher = None
    service._ack_queue_size = 0
    service._ack_queue = collections.deque()
    service._ack_lock = threading.Lock()
    service.stats = InputPlaneStats()
    worker.service = service

    stream = service._record_stream()
    assert list(stream) == []  # producer stepped aside, no records
    assert not service._inflight  # nothing appended to the new round
    assert (42, "round abandoned (spare park)") in worker.reported
