"""ImageNet ResNet-50 — the flagship throughput model.

Parity: reference model_zoo/imagenet_resnet50/imagenet_resnet50.py (Keras
builtin ResNet50 over JPEG-encoded records). Here the shared flax ResNet-50
(resnet50_subclass/resnet50_model.py) is instantiated with 1000 classes and
bfloat16 compute — the MXU-native dtype — while parameters stay float32.
This is the model used by bench.py and the BASELINE.md target metric
(examples/sec/chip).
"""

import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import decode_example

try:
    from resnet50_subclass.resnet50_model import ResNet50
except ImportError:
    from model_zoo.resnet50_subclass.resnet50_model import ResNet50


def custom_model(num_classes=1000, dtype="bfloat16"):
    return ResNet50(num_classes=num_classes, dtype=jnp.dtype(dtype))


def loss(output, labels):
    labels = labels.reshape(-1)
    probs = jnp.clip(output, 1e-7, 1.0)
    nll = -jnp.log(
        jnp.take_along_axis(probs, labels[:, None], axis=1)[:, 0]
    )
    return nll.mean()


def optimizer(lr=0.02, momentum=0.9):
    return optax.sgd(lr, momentum=momentum)


def dataset_fn(dataset, mode, _):
    def _parse_data(record):
        r = decode_example(record)
        # keep uint8: the model normalizes on device, so the host->device
        # transfer (often the E2E bottleneck) carries 1 byte/pixel not 4
        features = {"image": r["image"]}
        if mode == Mode.PREDICTION:
            return features
        return features, (r["label"].astype(np.int32) - 1).reshape(-1)

    # image decode is the CPU-heavy stage of this pipeline: run it on
    # the ordered parallel decode pool (in-order merge, so the stream
    # stays deterministic; docs/input_pipeline.md)
    dataset = dataset.map(_parse_data, num_parallel_calls=4)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }
