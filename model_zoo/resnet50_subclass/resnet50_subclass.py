"""ResNet-50 — subclass-style model-zoo module.

Parity: reference model_zoo/resnet50_subclass/resnet50_subclass.py —
``CustomModel(num_classes=10, dtype=...)``, softmax output, sparse
categorical cross-entropy on probabilities, SGD(0.02), raw-image
dataset_fn. Images arrive as decoded uint8 arrays (the TPU input pipeline
feeds fixed-shape decoded tensors; JPEG decode/resize happens at data-prep
time, see tests/test_utils.py IMAGENET schema).
"""

import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import decode_example

try:
    from resnet50_subclass.resnet50_model import ResNet50
except ImportError:
    from model_zoo.resnet50_subclass.resnet50_model import ResNet50


def CustomModel(num_classes=10, dtype="float32"):
    return ResNet50(num_classes=num_classes, dtype=jnp.dtype(dtype))


def loss(output, labels):
    labels = labels.reshape(-1)
    probs = jnp.clip(output, 1e-7, 1.0)
    nll = -jnp.log(
        jnp.take_along_axis(probs, labels[:, None], axis=1)[:, 0]
    )
    return nll.mean()


def optimizer(lr=0.02):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    def _parse_data(record):
        r = decode_example(record)
        features = {
            "image": (r["image"].astype(np.float32) / 255.0)
        }
        if mode == Mode.PREDICTION:
            return features
        # reference labels are 1-based (resnet50_subclass.py:199)
        return features, (r["label"].astype(np.int32) - 1).reshape(-1)

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }
