"""ResNet-50 building blocks in flax.

Parity: reference model_zoo/resnet50_subclass/resnet50_model.py
(IdentityBlock / ConvBlock keras layers) rebuilt as flax bottleneck blocks.
TPU-first choices: NHWC layout (XLA's native conv layout on TPU), BatchNorm
with zero-init on the last block norm (standard large-batch recipe),
configurable compute dtype so the conv/matmul path can run bfloat16 on the
MXU while parameters stay float32.
"""

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with optional projection shortcut."""

    filters: int
    strides: int = 1
    projection: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, training=False):
        norm = partial(
            nn.BatchNorm,
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = conv(
                self.filters * 4,
                (1, 1),
                strides=(self.strides, self.strides),
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    """ResNet-50 body: 3-4-6-3 bottleneck stages, softmax head."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, training=False):
        if isinstance(x, dict):
            x = x["image"]
        if x.dtype == jnp.uint8:
            # normalize on device: the input pipeline ships raw uint8 so
            # host->device traffic is 4x smaller than f32 images
            x = x.astype(self.dtype) * (1.0 / 255.0)
        else:
            x = x.astype(self.dtype)
        x = nn.Conv(
            64,
            (7, 7),
            strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, (filters, blocks) in enumerate(
            ((64, 3), (128, 4), (256, 6), (512, 3))
        ):
            strides = 1 if i == 0 else 2
            x = BottleneckBlock(
                filters, strides=strides, projection=True, dtype=self.dtype
            )(x, training=training)
            for _ in range(blocks - 1):
                x = BottleneckBlock(filters, dtype=self.dtype)(
                    x, training=training
                )
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        return nn.softmax(x)
