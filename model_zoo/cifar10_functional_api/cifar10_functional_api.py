"""CIFAR10 VGG-style CNN — functional-style model-zoo module.

Parity: reference model_zoo/cifar10_functional_api/cifar10_functional_api.py
— conv pairs (32, 64, 128) each followed by norm/relu, max-pool and
dropout (0.2/0.3/0.4), then Dense(10); same dataset_fn/loss/optimizer/
eval-metric contract, plus a PredictionOutputsProcessor that writes to an
ODPS table when credentials are present (reference :152-187). GroupNorm
replaces BatchNormalization (elasticity-invariant, no cross-replica sync).
"""

import os

import flax.linen as nn
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode, ODPSConfig
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.example import FixedLenFeature, parse_example
from elasticdl_tpu.worker.prediction_outputs_processor import (
    BasePredictionOutputsProcessor,
)


class Cifar10Model(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["image"]  # (B, 32, 32, 3)
        for filters, dropout_rate in ((32, 0.2), (64, 0.3), (128, 0.4)):
            for _ in range(2):
                x = nn.Conv(filters, (3, 3), padding="SAME", use_bias=True)(x)
                x = nn.GroupNorm(num_groups=8, epsilon=1e-6)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = nn.Dropout(dropout_rate, deterministic=not training)(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(10)(x)


def custom_model():
    return Cifar10Model()


def loss(output, labels):
    labels = labels.reshape(-1)
    return optax.softmax_cross_entropy_with_integer_labels(
        output, labels
    ).mean()


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    feature_spec = {"image": FixedLenFeature([32, 32, 3], np.float32)}
    if mode != Mode.PREDICTION:
        feature_spec["label"] = FixedLenFeature([1], np.int64)

    def _parse_data(record):
        r = parse_example(record, feature_spec)
        features = {"image": (r["image"] / 255.0).astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, r["label"].astype(np.int32)

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }


class PredictionOutputsProcessor(BasePredictionOutputsProcessor):
    """Writes predictions to ODPS when credentials are configured."""

    def __init__(self):
        if all(
            k in os.environ
            for k in (
                ODPSConfig.PROJECT_NAME,
                ODPSConfig.ACCESS_ID,
                ODPSConfig.ACCESS_KEY,
            )
        ):
            from elasticdl_tpu.data.odps_io import ODPSWriter

            self.odps_writer = ODPSWriter(
                os.environ[ODPSConfig.PROJECT_NAME],
                os.environ[ODPSConfig.ACCESS_ID],
                os.environ[ODPSConfig.ACCESS_KEY],
                os.environ.get(ODPSConfig.ENDPOINT),
                "cifar10_prediction_outputs",
                columns=["f" + str(i) for i in range(10)],
                column_types=["double"] * 10,
            )
        else:
            self.odps_writer = None

    def process(self, predictions, worker_id):
        if self.odps_writer:
            self.odps_writer.from_iterator(
                iter(np.asarray(predictions).tolist()), worker_id
            )
        else:
            logger.info("Predictions: %s", np.asarray(predictions))
