"""DeepFM with elastic (externally-stored) embedding tables.

Parity: reference model_zoo/deepfm_edl_embedding/deepfm_edl_embedding.py —
the same DeepFM architecture as deepfm_functional_api but with
``elasticdl.layers.Embedding`` (unbounded vocab, rows pulled on demand,
sparse gradients pushed back). Here the layers are
``elasticdl_tpu.nn.embedding.Embedding``: the table lives in the
master/PS store; the jitted step sees only the rows the batch touches
(nn/embedding.py module docstring describes the hoisted-lookup design).
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import FixedLenFeature, parse_example
from elasticdl_tpu.metrics import AUC

# frappe CTR vocabulary (reference data/recordio_gen/frappe_recordio_gen)
VOCAB_SIZE = 5384


TABLES = ("embedding", "id_bias")
# the hybrid split: the (conceptually multi-hundred-GB) feature table
# stays sharded on the PS fleet; the small first-order bias table is an
# ordinary parameter in the dense/allreduce world
HYBRID_SPLIT = {"embedding": "ps", "id_bias": "hbm"}


class DeepFMEdl(nn.Module):
    """DeepFM with a PER-TABLE embedding plane (docs/embedding_planes.md).

    ``embedding_plane`` selects each table's storage through the
    comm-plane interface (nn/comm_plane.py):

    - ``"ps"``: elastic Embedding — tables in the master/PS host store,
      rows pulled per batch, sparse grads pushed.
    - ``"hbm"``: HbmEmbedding — tables are real parameters, row-sharded
      over ``table_axis`` device HBM with all_to_all routing when a
      mesh is set, plain dense parameters when not (the BASELINE.json
      north star).
    - ``"hybrid"``: the declared split (``HYBRID_SPLIT``) — the big
      feature table on the PS fleet, the small bias table in the dense
      world; run it with the worker's ``--embedding_plane=hybrid``
      trainer mode so dense never round-trips through the PS.
    - ``"table:plane/table:plane"``: explicit per-table entries.

    Unset (``""``) keeps the historical mode-wide switch: PS layers
    without a mesh, HBM layers with one (or with force_hbm/collective)
    — one model body serves every mode either way.
    """

    embedding_dim: int = 64
    input_length: int = 10
    fc_unit: int = 64
    mesh: object = None
    vocab_size: int = VOCAB_SIZE
    table_axis: str = "data"
    embedding_plane: str = ""
    # force the HBM layer even without a mesh (single-device jnp.take —
    # the dense numerics twin the sharded path is validated against)
    force_hbm: bool = False
    # tables looked up with raw collectives (axis bound by an OUTER
    # shard_map — the multi-process elastic plane, parallel/elastic.py)
    collective: bool = False

    def _table_planes(self):
        from elasticdl_tpu.nn.comm_plane import resolve_table_planes

        if self.embedding_plane:
            return resolve_table_planes(
                self.embedding_plane, TABLES, hybrid_default=HYBRID_SPLIT
            )
        # legacy mode-wide switch, expressed through the same selector
        legacy = (
            "ps"
            if (
                self.mesh is None
                and not self.force_hbm
                and not self.collective
            )
            else "hbm"
        )
        return {t: legacy for t in TABLES}

    def _embedding(self, dim, name):
        from elasticdl_tpu.nn.comm_plane import make_embedding

        plane = self._table_planes()[name]
        if plane == "ps" and (self.collective or self.force_hbm):
            raise ValueError(
                "table %r rides the PS plane, which the collective/"
                "host-twin elastic forms cannot serve — train PS-plane "
                "tables on the parameter-server worker (hybrid mode)"
                % name
            )
        return make_embedding(
            plane,
            output_dim=dim,
            name=name,
            vocab_size=self.vocab_size,
            mesh=self.mesh,
            axis=self.table_axis,
            mask_zero=True,
            collective=self.collective,
        )

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["feature"].astype(jnp.int32)  # (B, L)
        mask = (ids != 0).astype(jnp.float32)[..., None]

        embeddings = self._embedding(self.embedding_dim, "embedding")(ids)
        embeddings = embeddings * mask

        emb_sum = embeddings.sum(axis=1)
        second_order = 0.5 * (
            jnp.square(emb_sum) - jnp.square(embeddings).sum(axis=1)
        ).sum(axis=1)

        id_bias = self._embedding(1, "id_bias")(ids)
        id_bias = id_bias * mask
        first_order = id_bias.sum(axis=(1, 2))
        fm_output = first_order + second_order

        nn_input = embeddings.reshape((embeddings.shape[0], -1))
        deep_output = nn.Dense(1)(nn.Dense(self.fc_unit)(nn_input))
        deep_output = deep_output.reshape(-1)

        logits = fm_output + deep_output
        probs = nn.sigmoid(logits).reshape((-1, 1))
        return {"logits": logits, "probs": probs}


def custom_model(
    embedding_dim=64,
    input_length=10,
    fc_unit=64,
    vocab_size=VOCAB_SIZE,
    embedding_plane="",
):
    return DeepFMEdl(
        embedding_dim=embedding_dim,
        input_length=input_length,
        fc_unit=fc_unit,
        vocab_size=vocab_size,
        embedding_plane=embedding_plane,
    )


def build_distributed_model(mesh, table_axis="data", **params):
    """ALLREDUCE-strategy hook: tables row-sharded over mesh HBM."""
    return DeepFMEdl(mesh=mesh, table_axis=table_axis, **params)


def build_collective_model(table_axis="data", **params):
    """Multi-process elastic hook: tables looked up with raw collectives
    inside the elastic plane's shard_map (parallel/elastic.py pairs this
    with ``param_shardings`` via ElasticDPTrainer's distributed_builder)."""
    return DeepFMEdl(collective=True, table_axis=table_axis, **params)


def build_host_model(**params):
    """Host twin of the collective model: same parameter structure,
    dense ``jnp.take`` lookups — the forward the elastic worker runs for
    evaluation/export against checkpoint-assembled full tables."""
    params.pop("table_axis", None)
    return DeepFMEdl(force_hbm=True, **params)


def param_shardings(mesh, table_axis="data", embedding_plane="", **_params):
    """PartitionSpecs for the HBM-resident tables; everything else
    (dense layers, optimizer moments of dense layers) replicates, and
    the tables' optimizer state co-shards with them automatically.
    PadDim0: vocab rows are inert beyond the declared size, so the
    elastic plane may zero-pad them to place on NON-DIVISOR world
    sizes (a kill 8 -> 7 keeps training instead of erroring).

    Per-table planes: only hbm-resident tables ARE parameters, so only
    they get specs — a ps-plane table lives in the PS store, not the
    params pytree (the elastic plane refuses such configs at layer
    construction; the PS worker's hybrid mode serves them)."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.nn.comm_plane import resolve_table_planes
    from elasticdl_tpu.parallel.elastic import PadDim0

    planes = (
        resolve_table_planes(
            embedding_plane, TABLES, hybrid_default=HYBRID_SPLIT
        )
        if embedding_plane
        else {t: "hbm" for t in TABLES}
    )
    spec = PadDim0(P(table_axis, None))
    return {
        name: {"table": spec}
        for name in TABLES
        if planes[name] == "hbm"
    }


def loss(output, labels):
    logits = output["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    feature_spec = {"feature": FixedLenFeature([10], np.int64)}
    if mode != Mode.PREDICTION:
        feature_spec["label"] = FixedLenFeature([1], np.int64)

    def _parse_data(record):
        r = parse_example(record, feature_spec)
        features = {"feature": r["feature"].astype(np.int64)}
        if mode == Mode.PREDICTION:
            return features
        return features, r["label"].astype(np.int32)

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: np.equal(
                (np.asarray(predictions).reshape(-1) > 0.0).astype(np.int32),
                np.asarray(labels).reshape(-1).astype(np.int32),
            )
        },
        "probs": {"auc": AUC()},
    }
