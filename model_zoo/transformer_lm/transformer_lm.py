"""Decoder-only transformer LM — the long-context model family.

No counterpart exists in the reference zoo (CNNs/DeepFM only, SURVEY.md
§5.7); this family exercises the framework's TPU-native scaling axes:

- ``data``  — batch data parallelism,
- ``model`` — tensor parallelism (parallel/sharding.py rules match this
  module's parameter names: query/key/value/out, mlp_up/mlp_down, embed),
- ``seq``   — sequence parallelism via ring attention
  (parallel/ring_attention.py) when constructed with ``mesh`` +
  ``seq_axis``.

Compute dtype is configurable (bfloat16 on the MXU by default for large
configs); RMSNorm + rotary embeddings keep the block cache/scan friendly.
"""

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import FixedLenFeature, parse_example
from elasticdl_tpu.parallel.ring_attention import (
    make_ring_attention,
    reference_attention,
)


def _rotary(x, positions):
    """Rotary position embedding over the last (head) dim."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000 ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, L, half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


class MoEMlp(nn.Module):
    """Mixture-of-experts MLP: top-k routing over the ``expert`` mesh
    axis (parallel/expert.py). Expert parameters are stacked on a leading
    (E,) dim sharded over the axis; the dense fallback (no mesh / no
    ``expert`` axis) computes every expert and combines by gate — the
    routed form's reference semantics. With ``aux_loss_coef > 0`` the
    Switch load-balancing loss is written to the ``aux_loss`` collection,
    which every step builder adds to the task loss (training/step.py)."""

    num_experts: int
    mlp_dim: int
    dtype: Any
    mesh: Any = None
    capacity_factor: float = 2.0
    num_selected: int = 1
    aux_loss_coef: float = 0.01

    @nn.compact
    def __call__(self, h):
        from elasticdl_tpu.parallel.expert import (
            load_balancing_loss,
            make_moe_fn,
            reference_moe,
        )
        from elasticdl_tpu.training.step import AUX_LOSS_COLLECTION

        d = h.shape[-1]
        e = self.num_experts
        gate_logits = nn.Dense(
            e, use_bias=False, dtype=self.dtype, name="gate"
        )(h)
        w_up = self.param(
            "experts_up",
            nn.initializers.lecun_normal(),
            (e, d, self.mlp_dim),
        )
        w_down = self.param(
            "experts_down",
            nn.initializers.lecun_normal(),
            (e, self.mlp_dim, d),
        )

        def expert_fn(params, tokens):
            up = tokens.astype(self.dtype) @ params["up"].astype(self.dtype)
            return nn.gelu(up) @ params["down"].astype(self.dtype)

        stacked = {"up": w_up, "down": w_down}
        tokens = h.reshape(-1, d)
        logits_flat = gate_logits.reshape(-1, e)
        aux = self.variable(
            AUX_LOSS_COLLECTION,
            "moe_balance",
            lambda: jnp.zeros((), jnp.float32),
        )
        if self.is_mutable_collection(AUX_LOSS_COLLECTION):
            # training applies pass state collections as mutable; eval
            # forwards are immutable and skip the write
            aux.value = self.aux_loss_coef * load_balancing_loss(
                logits_flat
            )
        use_routed = (
            self.mesh is not None and "expert" in self.mesh.axis_names
        )
        if use_routed:
            # shard the token stream over the data axis when present so
            # dp replicas route only their own slice (a P(None) spec
            # would all-gather and redo the MoE per replica)
            batch_axis = (
                "data" if "data" in self.mesh.axis_names else None
            )
            moe = make_moe_fn(
                self.mesh,
                expert_fn,
                expert_axis="expert",
                batch_axis=batch_axis,
                capacity_factor=self.capacity_factor,
                num_selected=self.num_selected,
            )
            out = moe(stacked, tokens, logits_flat)
        else:
            per_expert = [
                {"up": w_up[i], "down": w_down[i]} for i in range(e)
            ]
            out = reference_moe(
                expert_fn,
                per_expert,
                tokens,
                logits_flat,
                num_selected=self.num_selected,
            )
        return out.reshape(h.shape).astype(h.dtype)


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Any
    attention_fn: Any
    num_experts: int = 0
    mesh: Any = None
    moe_capacity_factor: float = 2.0
    moe_num_selected: int = 1
    moe_aux_loss_coef: float = 0.01

    @nn.compact
    def __call__(self, x, positions):
        h = nn.RMSNorm(dtype=self.dtype)(x)
        dense = functools.partial(
            nn.DenseGeneral,
            features=(self.num_heads, self.head_dim),
            axis=-1,
            use_bias=False,
            dtype=self.dtype,
        )
        q = _rotary(dense(name="query")(h), positions)
        k = _rotary(dense(name="key")(h), positions)
        v = dense(name="value")(h)
        attn = self.attention_fn(q, k, v)
        attn = nn.DenseGeneral(
            features=x.shape[-1],
            axis=(-2, -1),
            use_bias=False,
            dtype=self.dtype,
            name="out",
        )(attn)
        x = x + attn
        h = nn.RMSNorm(dtype=self.dtype)(x)
        if self.num_experts:
            h = MoEMlp(
                num_experts=self.num_experts,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                mesh=self.mesh,
                capacity_factor=self.moe_capacity_factor,
                num_selected=self.moe_num_selected,
                aux_loss_coef=self.moe_aux_loss_coef,
                name="moe_mlp",
            )(h)
        else:
            h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_up")(h)
            h = nn.gelu(h)
            h = nn.Dense(x.shape[-1], dtype=self.dtype, name="mlp_down")(h)
        return x + h


class TransformerLM(nn.Module):
    vocab_size: int = 1024
    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 16
    embed_dim: int = 64
    mlp_dim: int = 256
    dtype: Any = jnp.float32
    mesh: Any = None
    seq_axis: Any = None
    # Pallas fused-attention kernel (single-chip path; the mesh/seq_axis
    # path uses the fused ring). Trains blockwise since round 2 — the
    # backward recomputes p per tile from the saved logsumexp.
    use_flash: bool = True
    # >0 turns every block's MLP into a top-k MoE; expert parameters
    # shard over the mesh's 'expert' axis when present (parallel/expert)
    num_experts: int = 0
    moe_capacity_factor: float = 2.0
    moe_num_selected: int = 1  # top-k routing (2 = GShard top-2)
    moe_aux_loss_coef: float = 0.01  # Switch load-balancing loss weight

    @nn.compact
    def __call__(self, features, training=False):
        tokens = (
            features["tokens"] if isinstance(features, dict) else features
        )
        tokens = tokens.astype(jnp.int32)
        b, l = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(l, dtype=jnp.int32), (b, l))

        if self.mesh is not None and self.seq_axis is not None:
            attention_fn = make_ring_attention(
                self.mesh, self.seq_axis, causal=True,
                use_flash=self.use_flash,
            )
        else:
            # flash above the measured win threshold, XLA below / for
            # lengths the kernel can't tile (one policy home:
            # ops/flash_attention.pick_causal_attention)
            from elasticdl_tpu.ops.flash_attention import (
                pick_causal_attention,
            )

            attention_fn = pick_causal_attention(l, self.use_flash)

        embed_layer = nn.Embed(
            self.vocab_size,
            self.embed_dim,
            dtype=self.dtype,
            name="embed",
        )
        x = embed_layer(tokens)
        for i in range(self.num_layers):
            x = Block(
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                attention_fn=attention_fn,
                num_experts=self.num_experts,
                mesh=self.mesh,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_num_selected=self.moe_num_selected,
                moe_aux_loss_coef=self.moe_aux_loss_coef,
                name="block_%d" % i,
            )(x, positions)
        x = nn.RMSNorm(dtype=self.dtype)(x)
        # weight-tied LM head (reads the vocab-sharded embed table)
        logits = embed_layer.attend(x.astype(jnp.float32))
        return logits


class StageBlocks(nn.Module):
    """One pipeline stage: a sequential run of transformer blocks.

    The pipeline stage template (parallel/pipeline.py PipelinedStack):
    maps (b, l, d) activations to the same shape; rotary positions are
    recomputed per stage from the activation length (identical across
    examples, so nothing needs to ride the ring besides activations)."""

    n_layers: int
    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Any
    use_flash: bool = True

    @nn.compact
    def __call__(self, x):
        b, l = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(
            jnp.arange(l, dtype=jnp.int32), (b, l)
        )
        from elasticdl_tpu.ops.flash_attention import (
            pick_causal_attention,
        )

        attention_fn = pick_causal_attention(l, self.use_flash)
        for i in range(self.n_layers):
            x = Block(
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                attention_fn=attention_fn,
                name="block_%d" % i,
            )(x, positions)
        return x


class PipelinedTransformerLM(nn.Module):
    """TransformerLM with its block stack run as pipeline stages.

    Embed + head (weight-tied) replicate outside the ring; the blocks
    group into ``pipeline_stages`` stages whose parameters live only on
    their stage's devices (mesh axis ``pipe``), composing with ``data``
    batch parallelism on the same mesh (pp x dp)."""

    vocab_size: int = 1024
    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 16
    embed_dim: int = 64
    mlp_dim: int = 256
    dtype: Any = jnp.float32
    mesh: Any = None
    pipeline_stages: int = 2
    microbatches: int = 0
    use_flash: bool = True
    # in-step raw-collective ring for the multi-process elastic plane
    # (applied inside the weighted step's shard_map; mesh stays None)
    collective: bool = False

    @nn.compact
    def __call__(self, features, training=False):
        tokens = (
            features["tokens"] if isinstance(features, dict) else features
        )
        tokens = tokens.astype(jnp.int32)
        if self.num_layers % self.pipeline_stages:
            raise ValueError(
                "num_layers %d must divide into %d pipeline stages"
                % (self.num_layers, self.pipeline_stages)
            )
        embed_layer = nn.Embed(
            self.vocab_size,
            self.embed_dim,
            dtype=self.dtype,
            name="embed",
        )
        x = embed_layer(tokens)
        from elasticdl_tpu.parallel.pipeline import PipelinedStack

        x = PipelinedStack(
            stage_template=StageBlocks(
                n_layers=self.num_layers // self.pipeline_stages,
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                use_flash=self.use_flash,
            ),
            n_stages=self.pipeline_stages,
            mesh=self.mesh,
            microbatches=self.microbatches,
            collective=self.collective,
            name="pipe",
        )(x)
        x = nn.RMSNorm(dtype=self.dtype)(x)
        logits = embed_layer.attend(x.astype(jnp.float32))
        return logits


_PIPELINE_SUPPORTED_PARAMS = frozenset(
    {
        "vocab_size",
        "num_layers",
        "num_heads",
        "head_dim",
        "embed_dim",
        "mlp_dim",
        "use_flash",
    }
)


def _check_pipeline_params(params):
    """Reject model params the pipelined form would silently drop —
    training a DIFFERENT model than asked for (e.g. dense instead of
    MoE). Shared by both pipelined entry points so their supported
    sets cannot drift."""
    unsupported = set(params) - _PIPELINE_SUPPORTED_PARAMS
    if unsupported:
        raise ValueError(
            "pipeline_stages > 1 does not support model params %s "
            "(pipeline composes with data parallelism only for "
            "now; MoE/seq-parallel pipelined configs are not "
            "implemented)" % sorted(unsupported)
        )


def build_distributed_model(
    mesh, pipeline_stages=0, microbatches=0, dtype="float32", **params
):
    """Zoo hook for the ALLREDUCE trainers: with ``pipeline_stages > 1``
    builds the pipelined form over the mesh's pipe axis (pair with
    :func:`param_shardings` and :func:`mesh_axes`); otherwise the plain
    model over the mesh."""
    stages = int(pipeline_stages)
    # consumed by param_shardings/mesh_axes (placement), not by the
    # model itself
    params.pop("shard_vocab", None)
    params.pop("tensor_parallel", None)
    params.pop("min_tensor_parallel", None)
    if stages > 1:
        _check_pipeline_params(params)
        return PipelinedTransformerLM(
            mesh=mesh,
            pipeline_stages=stages,
            microbatches=int(microbatches),
            dtype=jnp.dtype(dtype),
            **params,
        )
    return custom_model(mesh=mesh, dtype=dtype, **params)


def build_collective_model(
    pipeline_stages=0, microbatches=0, dtype="float32", **params
):
    """Zoo hook for the MULTI-PROCESS elastic plane: the pipelined
    transformer in its raw-collective form, applied inside the weighted
    step's shard_map over a ("data", "pipe") mesh (see
    parallel/pipeline.collective_pipeline_apply). The mesh axis layout
    comes from :func:`mesh_axes`; stage parameters shard per
    :func:`param_shardings`. Requires ``pipeline_stages > 1`` — plain
    (non-sharding) configs train replicated via ``custom_model`` and
    never route here (the worker gates on param_shardings' probe)."""
    stages = int(pipeline_stages)
    if params.pop("shard_vocab", None):
        # param_shardings would declare the embed table P("data", None)
        # while this model builds a full-vocab nn.Embed — the step would
        # feed the local shard to a full-table module. Fail fast with
        # the boundary instead of crash-looping at establish.
        raise ValueError(
            "shard_vocab is not supported on the multi-process elastic "
            "plane yet (the pipelined collective form keeps the embed "
            "table replicated); drop shard_vocab, or use the "
            "single-process ALLREDUCE strategy for vocab-sharded "
            "training"
        )
    if stages <= 1:
        raise ValueError(
            "build_collective_model needs pipeline_stages > 1; "
            "non-pipelined configs train on the replicated plane"
        )
    _check_pipeline_params(params)
    return PipelinedTransformerLM(
        mesh=None,
        collective=True,
        pipeline_stages=stages,
        microbatches=int(microbatches),
        dtype=jnp.dtype(dtype),
        **params,
    )


def param_shardings(
    mesh,
    pipeline_stages=0,
    shard_vocab=False,
    tensor_parallel=0,
    min_tensor_parallel=0,
    **_params,
):
    """Stacked stage parameters shard leaf-dim-0 over ``pipe``; with
    ``shard_vocab`` the token-embedding table additionally row-shards
    its vocab over ``data`` (the weight-tied LM head then contracts a
    vocab-sharded table — XLA inserts the collectives from the
    placement, the HBM-embedding recipe applied to the LM family).

    With ``tensor_parallel > 1`` the dense model itself shards over the
    2D ``data x model`` mesh: the name-pattern TP rules of
    parallel/sharding.py (qkv/out heads, MLP hidden, vocab) emitted as
    real specs — the PLAIN module then trains under the elastic
    trainer's pjit/GSPMD dense path, parameters placed by NamedSharding
    instead of replicated everywhere (docs/distributed.md), unlocking
    dense models bigger than one device's HBM inside the elastic world.

    ``min_tensor_parallel`` opts into the elastic LAYOUT RE-SOLVE
    (docs/distributed.md "Layout re-solve") without freezing a degree:
    the TP specs are emitted (routing the config onto the pjit dense
    plane — the worker's ``_zoo_wants_pjit_dense`` probe sees the
    ``model`` axis), the layout solver picks the actual degree per
    world size, and the value acts as the tp FLOOR the master derives
    its world-size multiple from — so a solver-chosen degree can never
    form a world the mesh rejects. The TP spec patterns themselves are
    degree-free (the mesh's model-axis size carries the degree), which
    is what makes a per-resize degree change sound.

    ``mesh=None`` is the capability probe (does this config shard at
    all?) — answered from the params alone."""
    from jax.sharding import PartitionSpec as P

    specs = {}
    tp = max(int(tensor_parallel), int(min_tensor_parallel))
    if tp > 1 and int(pipeline_stages) > 1:
        raise ValueError(
            "tensor_parallel and pipeline_stages cannot combine yet: "
            "the pjit dense path and the collective pipeline use "
            "different step builders — pick one"
        )
    if tp > 1 and shard_vocab:
        raise ValueError(
            "shard_vocab is redundant with tensor_parallel (the TP "
            "rules already vocab-shard the embed table, over 'model')"
        )
    if tp > 1 and (mesh is None or "model" in mesh.axis_names):
        from elasticdl_tpu.parallel.sharding import tp_param_specs

        specs.update(tp_param_specs())
    if int(pipeline_stages) > 1 and (
        mesh is None or "pipe" in mesh.axis_names
    ):
        specs["pipe"] = {"stages": {"**": P("pipe")}}
    if shard_vocab and (mesh is None or "data" in mesh.axis_names):
        specs["embed"] = {"embedding": P("data", None)}
    return specs or None


def mesh_axes(
    n_devices,
    pipeline_stages=0,
    tensor_parallel=0,
    min_tensor_parallel=0,
    **_params,
):
    """Zoo hook: mesh shape for this model's parallelism config.

    With only ``min_tensor_parallel`` set this answers the FLOOR layout
    (tp = the floor) — the static fallback the layout planner starts
    from and re-solves away from once the model profile exists. The
    master's world-size-multiple derivation keeps every formable world
    a multiple of the floor, so the divisibility check here cannot
    fire on the planner's watch."""
    stages = int(pipeline_stages)
    tp = max(int(tensor_parallel), int(min_tensor_parallel))
    if tp > 1:
        if stages > 1:
            raise ValueError(
                "tensor_parallel does not combine with pipeline_stages"
            )
        if n_devices % tp:
            raise ValueError(
                "%d devices do not divide into tensor_parallel=%d"
                % (n_devices, tp)
            )
        # row-major reshape: consecutive devices fill the model axis
        # first, so each tp group is one contiguous device block
        return {"data": n_devices // tp, "model": tp}
    if stages > 1:
        if n_devices % stages:
            raise ValueError(
                "%d devices do not divide into %d pipeline stages"
                % (n_devices, stages)
            )
        return {"data": n_devices // stages, "pipe": stages}
    return None


def custom_model(
    vocab_size=1024,
    num_layers=2,
    num_heads=4,
    head_dim=16,
    embed_dim=64,
    mlp_dim=256,
    dtype="float32",
    mesh=None,
    seq_axis=None,
    use_flash=True,
    num_experts=0,
    moe_capacity_factor=2.0,
    moe_num_selected=1,
    moe_aux_loss_coef=0.01,
    # consumed by build_distributed_model (the ALLREDUCE job path swaps
    # in PipelinedTransformerLM) / param_shardings (tensor_parallel
    # placement — the pjit dense path trains THIS plain module);
    # accepted here so one --model_params string serves both the plain
    # spec and the distributed hooks
    pipeline_stages=0,
    microbatches=0,
    tensor_parallel=0,
    min_tensor_parallel=0,
    shard_vocab=False,
):
    return TransformerLM(
        vocab_size=vocab_size,
        num_layers=num_layers,
        num_heads=num_heads,
        head_dim=head_dim,
        embed_dim=embed_dim,
        mlp_dim=mlp_dim,
        dtype=jnp.dtype(dtype),
        mesh=mesh,
        seq_axis=seq_axis,
        use_flash=use_flash,
        num_experts=num_experts,
        moe_capacity_factor=moe_capacity_factor,
        moe_num_selected=moe_num_selected,
        moe_aux_loss_coef=moe_aux_loss_coef,
    )


def loss(output, labels):
    """Next-token cross entropy; position 0 predicts token 1, etc."""
    logits = output[:, :-1]
    targets = labels.astype(jnp.int32)[:, 1:]
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, targets
    ).mean()


def optimizer(lr=3e-3):
    return optax.adamw(lr)


def dataset_fn(dataset, mode, _):
    def _parse_data(record):
        r = parse_example(record, {"tokens": FixedLenFeature([64], np.int64)})
        tokens = r["tokens"].astype(np.int32)
        features = {"tokens": tokens}
        if mode == Mode.PREDICTION:
            return features
        return features, tokens

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    def _token_accuracy(labels, predictions):
        pred = np.argmax(np.asarray(predictions)[:, :-1], axis=-1)
        tgt = np.asarray(labels)[:, 1:]
        return (pred == tgt).reshape(-1)

    return {"token_accuracy": _token_accuracy}
