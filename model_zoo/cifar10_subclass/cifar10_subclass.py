"""CIFAR10 CNN — subclass-style model-zoo module.

Parity: reference model_zoo/cifar10_subclass/cifar10_subclass.py — the same
network as cifar10_functional_api defined as a ``CustomModel`` class.
"""

import flax.linen as nn
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import FixedLenFeature, parse_example


class CustomModel(nn.Module):
    @nn.compact
    def __call__(self, inputs, training=False):
        x = inputs["image"]
        for filters, dropout_rate in ((32, 0.2), (64, 0.3), (128, 0.4)):
            for _ in range(2):
                x = nn.Conv(filters, (3, 3), padding="SAME", use_bias=True)(x)
                x = nn.GroupNorm(num_groups=8, epsilon=1e-6)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = nn.Dropout(dropout_rate, deterministic=not training)(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(10)(x)


def loss(output, labels):
    labels = labels.reshape(-1)
    return optax.softmax_cross_entropy_with_integer_labels(
        output, labels
    ).mean()


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    feature_spec = {"image": FixedLenFeature([32, 32, 3], np.float32)}
    if mode != Mode.PREDICTION:
        feature_spec["label"] = FixedLenFeature([1], np.int64)

    def _parse_data(record):
        r = parse_example(record, feature_spec)
        features = {"image": (r["image"] / 255.0).astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, r["label"].astype(np.int32)

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }
