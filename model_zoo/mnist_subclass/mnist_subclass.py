"""MNIST CNN — subclass-style model-zoo module.

Parity: reference model_zoo/mnist_subclass/mnist_subclass.py — the same
network as mnist_functional_api but defined as a model *class*
(``CustomModel``) resolved through the class path of the zoo contract
(common/model_utils.py load_model_from_module). GroupNorm replaces
BatchNormalization (batch-size invariant under elasticity; no cross-replica
stat sync in the jitted step).
"""

import flax.linen as nn
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import FixedLenFeature, parse_example


class CustomModel(nn.Module):
    channel_last: bool = True

    @nn.compact
    def __call__(self, inputs, training=False):
        x = inputs["image"]
        x = (
            x[..., None]
            if self.channel_last
            else x[:, None, :, :].transpose(0, 2, 3, 1)
        )
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if training:
            x = nn.Dropout(0.25, deterministic=False)(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(10)(x)


def loss(output, labels):
    labels = labels.reshape(-1)
    return optax.softmax_cross_entropy_with_integer_labels(
        output, labels
    ).mean()


def optimizer(lr=0.01):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    feature_spec = {"image": FixedLenFeature([28, 28], np.float32)}
    if mode != Mode.PREDICTION:
        feature_spec["label"] = FixedLenFeature([1], np.int64)

    def _parse_data(record):
        r = parse_example(record, feature_spec)
        features = {"image": (r["image"] / 255.0).astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, r["label"].astype(np.int32)

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }
