"""Iris DNN over ODPS table rows.

Parity: reference model_zoo/odps_iris_dnn_model/odps_iris_dnn_model.py —
a Dense(3) classifier over 4 numeric columns; ``dataset_fn`` consumes raw
table rows (sequences of column values) and uses ``metadata.column_names``
to split out the label column, exactly the reference's contract for the
ODPS reader path.
"""

import flax.linen as nn
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode


class IrisModel(nn.Module):
    @nn.compact
    def __call__(self, inputs, training=False):
        x = inputs.reshape((inputs.shape[0], -1))
        return nn.Dense(3)(x)


def custom_model():
    return IrisModel()


def loss(output, labels):
    labels = labels.reshape(-1).astype(np.int32)
    return optax.softmax_cross_entropy_with_integer_labels(
        output, labels
    ).mean()


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, metadata):
    label_col_name = "class"

    def _parse_data(record):
        record = np.asarray(record, dtype=np.float32)

        def _features_without_label(label_col_ind):
            features = np.concatenate(
                [record[:label_col_ind], record[label_col_ind + 1 :]]
            )
            return features.reshape((4, 1))

        if mode != Mode.PREDICTION:
            if label_col_name not in metadata.column_names:
                raise ValueError(
                    "Missing the label column '%s' in the retrieved "
                    "ODPS table." % label_col_name
                )
            label_col_ind = metadata.column_names.index(label_col_name)
            labels = record[label_col_ind].reshape((1,))
            return _features_without_label(label_col_ind), labels
        if label_col_name in metadata.column_names:
            label_col_ind = metadata.column_names.index(label_col_name)
            return _features_without_label(label_col_ind)
        return record.reshape((4, 1))

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=200)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }
