"""MNIST CNN — functional-style model-zoo module.

Parity: reference model_zoo/mnist_functional_api/mnist_functional_api.py —
same architecture (conv32 -> conv64 -> norm -> pool -> dropout -> dense10),
loss, optimizer, dataset_fn and eval metric contract, rebuilt as a flax
module. BatchNormalization is replaced by GroupNorm: it is batch-size
invariant, so elastic changes to per-worker batch size or world size never
shift normalization statistics, and no cross-replica stat sync is needed
inside the jitted step.
"""

import flax.linen as nn
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example import FixedLenFeature, parse_example


class MnistModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["image"]  # (B, 28, 28) float32 in [0, 1]
        x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not training)(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(10)(x)


def custom_model():
    return MnistModel()


def loss(output, labels):
    labels = labels.reshape(-1)
    return optax.softmax_cross_entropy_with_integer_labels(
        output, labels
    ).mean()


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    feature_spec = {"image": FixedLenFeature([28, 28], np.float32)}
    if mode != Mode.PREDICTION:
        feature_spec["label"] = FixedLenFeature([1], np.int64)

    def _parse_data(record):
        r = parse_example(record, feature_spec)
        features = {"image": (r["image"] / 255.0).astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, r["label"].astype(np.int32)

    dataset = dataset.map(_parse_data)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: np.equal(
            np.argmax(predictions, axis=1).astype(np.int32),
            np.asarray(labels).reshape(-1).astype(np.int32),
        )
    }
