#!/usr/bin/env bash
# Build the image family (parity: reference elasticdl/docker/build_all.sh):
#   elasticdl-tpu:dev — toolchain + framework, for TPU VM development
#   elasticdl-tpu     — runtime layer job pods run on
#   elasticdl-tpu:ci  — runtime + tests + zoo, for in-cluster CI
# Run from the repo root. BASE_IMAGE selects the python base (a TPU VM
# image already carrying libtpu also works).
set -euo pipefail

if [[ ! -d .git ]]; then
    echo "run this script from the root of the source tree" >&2
    exit 1
fi

base_img="${BASE_IMAGE:-python:3.11-slim}"

docker build -t elasticdl-tpu:dev -f docker/Dockerfile.dev \
    --build-arg BASE_IMAGE="${base_img}" .
docker build -t elasticdl-tpu -f docker/Dockerfile \
    --build-arg BASE_IMAGE="${base_img}" .
docker build -t elasticdl-tpu:ci -f docker/Dockerfile.ci .
