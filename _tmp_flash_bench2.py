import time
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.parallel.ring_attention import reference_attention

ITERS = 20

def bench(fn, b, l, h, d):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
    grad = jax.grad(loss, argnums=(0, 1, 2))
    @jax.jit
    def run(q, k, v):
        def step(carry, i):
            gq, gk, gv = grad(q + carry * 1e-30, k, v)
            return carry + gq.astype(jnp.float32).sum() * 1e-30, ()
        c, _ = lax.scan(step, jnp.float32(0.0), jnp.arange(ITERS))
        return c
    float(run(q, k, v))
    t0 = time.perf_counter(); float(run(q, k, v))
    return (time.perf_counter() - t0) / ITERS

for b, l in ((2, 8192), (1, 16384), (1, 32768)):
    h, d = 8, 64
    row = f"b={b} L={l}:"
    try:
        t = bench(lambda q, k, v: flash_attention(q, k, v, True), b, l, h, d)
        row += f" flash {t*1e3:8.1f}ms"
    except Exception as e:
        row += f" flash FAIL({type(e).__name__})"
    try:
        t = bench(lambda q, k, v: reference_attention(q, k, v, causal=True), b, l, h, d)
        row += f"  ref {t*1e3:8.1f}ms"
    except Exception as e:
        row += f"  ref FAIL({type(e).__name__})"
    print(row, flush=True)
