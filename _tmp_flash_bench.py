"""Flash vs reference attention fwd+bwd on the TPU chip (scan-measured,
DCE-proof: grads folded into the carry)."""
import time
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.parallel.ring_attention import reference_attention

ITERS = 100

def bench(fn, b, l, h, d):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def step(carry, i):
            gq, gk, gv = grad(q + carry * 1e-30, k, v)
            return carry + gq.astype(jnp.float32).sum() * 1e-30 + gk.astype(jnp.float32).sum() * 1e-30 + gv.astype(jnp.float32).sum() * 1e-30, ()
        c, _ = lax.scan(step, jnp.float32(0.0), jnp.arange(ITERS))
        return c

    float(run(q, k, v))  # compile+warm
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return best / ITERS

for l in (512, 1024, 2048, 4096):
    b, h, d = 4, 8, 64
    t_flash = bench(lambda q, k, v: flash_attention(q, k, v, True), b, l, h, d)
    t_ref = bench(lambda q, k, v: reference_attention(q, k, v, causal=True), b, l, h, d)
    # causal fwd+bwd ~ 3.5x fwd flops; fwd = 2*b*h*l^2*d (halved causal)
    fl = 3.5 * 2 * b * h * l * l * d / 2
    print(f"L={l}: flash {t_flash*1e3:7.2f}ms ({fl/t_flash/1e12:5.1f} TF/s)  "
          f"ref {t_ref*1e3:7.2f}ms ({fl/t_ref/1e12:5.1f} TF/s)  "
          f"speedup {t_ref/t_flash:.2f}x", flush=True)
